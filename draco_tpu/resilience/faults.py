"""Deterministic fault injection — the chaos counterpart of ``attacks.py``.

The adversary schedules (draco_tpu/rng.py) make Byzantine behavior a seeded,
replayable experiment input; this module extends the same discipline to the
faults DRACO's code contract does NOT model (ISSUE 6): non-finite gradients
from faulty-but-honest workers, corruption past the s budget, dead or hung
prefetch threads, and SIGTERM mid-run. A :class:`FaultPlan` is parsed from
``cfg.fault_spec`` — a comma-separated list of ``kind@step`` events — so the
same plan replays bit-for-bit across runs, regimes (eager vs chunked) and
processes, which is what lets ``tools/chaos_run.py`` classify each fault
class as *masked* (final state bitwise-equal to a fault-free run) or
*gracefully degraded* (named error / resumable checkpoint / correct
terminal heartbeat state) instead of "something happened".

Event grammar (``FaultPlan.parse``)::

    kind@step[:w<worker>][:d<seconds>]

    nan_grad@5          worker (seeded draw) emits a NaN gradient at step 5
    inf_grad@5:w2       worker 2 emits an Inf gradient at step 5
    over_budget@7       step 7's adversary row is pushed to s+1 live
                        adversaries (beyond the code's locator budget)
    straggle@5:w3       worker 3 drops (sustained) from step 5 to the end
                        of the run — the heterogeneous-fleet / preempted-
                        worker fault the approx code family (ISSUE 8)
                        absorbs as scheduled erasures, NOT a one-shot
                        crash: the worker's rows simply stop arriving
    straggle@5:w3:d4    ... and recovers after 4 steps (absent 5..8)
    prefetch_crash@5    the prefetcher host fn raises InjectedFaultError
                        the first time step 5's data is requested
    prefetch_hang@5:d6  ... sleeps 6 s instead (a stalled worker thread)
    sigterm@5           SIGTERM is raised in-process once step 5 completes
    ckpt_corrupt@8      consumed by tools/chaos_run.py: flip bytes in the
    ckpt_truncate@8     step-8 checkpoint / truncate it, then resume

In-graph kinds are applied with the same branch-free ``jnp.where`` masking
as ``attacks.inject_plain`` — the fault is part of the compiled program
(config-static: an empty plan compiles the exact unfaulted program, and a
given plan compiles once; no steady-state retraces). Host kinds fire
one-shot through :class:`HostFaultInjector` so a supervised retry
(resilience/supervisor.py) re-executes the request cleanly — exactly how a
transient real-world fault behaves.
"""

from __future__ import annotations

import dataclasses
import functools
import re
from typing import Optional, Tuple

import numpy as np

# in-graph kinds corrupt the step's compiled inputs; schedule kinds mutate
# the seeded host schedules before upload (over_budget → adversary rows,
# straggle → straggler/present rows); host kinds fire in the host loop /
# prefetcher; ckpt kinds are consumed by tools/chaos_run.py
INGRAPH_KINDS = ("nan_grad", "inf_grad")
SCHEDULE_KINDS = ("over_budget", "straggle")
HOST_KINDS = ("prefetch_crash", "prefetch_hang", "sigterm")
CKPT_KINDS = ("ckpt_corrupt", "ckpt_truncate")
FAULT_KINDS = INGRAPH_KINDS + SCHEDULE_KINDS + HOST_KINDS + CKPT_KINDS

_EVENT_RE = re.compile(r"^(?P<kind>[a-z_]+)@(?P<step>\d+)"
                       r"(?::w(?P<worker>\d+))?(?::d(?P<dur>[\d.]+))?$")


class InjectedFaultError(RuntimeError):
    """The named error a ``prefetch_crash`` event raises — distinguishable
    from any organic failure, so chaos tests can assert the supervision
    path masked exactly the injected fault and nothing else."""


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    kind: str
    step: int  # 1-based training step the event targets
    worker: Optional[int] = None  # in-graph/straggle kinds: the target row
    # ``:d<n>`` payload. prefetch_hang: seconds the worker thread sleeps
    # (None → 30 s). straggle: dwell in STEPS before the worker recovers
    # (None → sustained to the end of the run — the spot-instance shape).
    duration_s: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """An immutable, seed-deterministic set of fault events."""

    events: Tuple[FaultEvent, ...]
    seed: int
    num_workers: int

    @classmethod
    def parse(cls, spec: str, seed: int, num_workers: int) -> "FaultPlan":
        events = []
        for i, tok in enumerate(t.strip() for t in spec.split(",")):
            if not tok:
                continue
            m = _EVENT_RE.match(tok)
            if not m:
                raise ValueError(
                    f"fault_spec event {tok!r} does not match "
                    f"'kind@step[:w<worker>][:d<seconds>]'"
                )
            kind, step = m.group("kind"), int(m.group("step"))
            if kind not in FAULT_KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r}; known: "
                    f"{'|'.join(FAULT_KINDS)}"
                )
            if step < 1:
                raise ValueError(f"fault step must be >= 1 in {tok!r}")
            worker = m.group("worker")
            if worker is not None:
                worker = int(worker)
                if worker >= num_workers:
                    raise ValueError(
                        f"fault worker {worker} out of range "
                        f"(num_workers={num_workers}) in {tok!r}"
                    )
            elif kind in INGRAPH_KINDS + ("straggle",):
                # seeded per-event draw — the same "every participant can
                # recompute it" property as rng.adversary_schedule
                r = np.random.RandomState((seed ^ 0x4641554C) + 7919 * i)
                worker = int(r.randint(num_workers))
            dur = m.group("dur")
            if dur is not None and kind == "straggle" \
                    and float(dur) != int(float(dur)):
                # :d is float SECONDS for host kinds but integer STEPS for
                # straggle — reject here rather than silently flooring
                raise ValueError(
                    f"straggle dwell is a whole number of steps, got "
                    f"d{dur} in {tok!r}"
                )
            events.append(FaultEvent(
                kind=kind, step=step, worker=worker,
                duration_s=float(dur) if dur is not None else None,
            ))
        return cls(events=tuple(events), seed=seed, num_workers=num_workers)

    def of_kind(self, *kinds: str) -> Tuple[FaultEvent, ...]:
        return tuple(e for e in self.events if e.kind in kinds)

    @property
    def ingraph_events(self) -> Tuple[FaultEvent, ...]:
        return self.of_kind(*INGRAPH_KINDS)


@functools.lru_cache(maxsize=64)
def _cached_plan(spec: str, seed: int, num_workers: int) -> FaultPlan:
    return FaultPlan.parse(spec, seed, num_workers)


def plan_from_cfg(cfg) -> Optional[FaultPlan]:
    """The cfg's parsed plan, or None when no faults are configured (the
    common case — every consumer below is an exact no-op then)."""
    if not getattr(cfg, "fault_spec", ""):
        return None
    return _cached_plan(cfg.fault_spec, cfg.seed, cfg.num_workers)


# ---- in-graph injection ----------------------------------------------------


def corrupt_grads(grads, cfg, step):
    """Branch-free NaN/Inf injection into the (n, ...) per-worker gradient
    stack at the plan's in-graph events — IDENTITY (no added ops, no graph
    change) when the plan has none. ``step`` may be a traced scalar (the
    scanned drivers feed it per-iteration), so the comparison runs in-graph
    against the events' tiny static step/worker vectors: the same masked
    ``jnp.where`` discipline as attacks.inject_plain, and no retrace ever
    (the plan is config-static)."""
    plan = plan_from_cfg(cfg)
    if plan is None or not plan.ingraph_events or step is None:
        return grads
    import jax.numpy as jnp

    n = grads.shape[0]
    mask = jnp.zeros((n,), bool)
    payload = jnp.zeros((n,), grads.dtype)
    for ev in plan.ingraph_events:
        hit = (jnp.asarray(ev.step, jnp.int32) ==
               jnp.asarray(step, jnp.int32))
        row = jnp.arange(n) == ev.worker
        mask = mask | (hit & row)
        val = jnp.nan if ev.kind == "nan_grad" else jnp.inf
        payload = jnp.where(hit & row, jnp.asarray(val, grads.dtype),
                            payload)
    shape = (n,) + (1,) * (grads.ndim - 1)
    return jnp.where(mask.reshape(shape), payload.reshape(shape), grads)


def apply_over_budget(adv_schedule: np.ndarray, plan: Optional[FaultPlan],
                      worker_fail: int) -> np.ndarray:
    """Host-side schedule mutation for ``over_budget`` events: the targeted
    steps' adversary rows gain seeded extra workers until s+1 are live —
    one corruption past the code's locator budget, the regime where exact
    recovery is impossible and the guard (resilience/guards.py) is the only
    thing standing between a silently poisoned update and a skipped one.
    Returns the (possibly copied) schedule; the input is never mutated."""
    if plan is None:
        return adv_schedule
    events = plan.of_kind("over_budget")
    if not events:
        return adv_schedule
    adv = np.array(adv_schedule, copy=True)
    n = adv.shape[1]
    want = min(worker_fail + 1, n)
    for ev in events:
        if ev.step >= adv.shape[0]:
            continue  # beyond the run's schedule table — inert
        row = adv[ev.step]
        r = np.random.RandomState((plan.seed ^ 0x0B0D6E7) + ev.step)
        order = r.permutation(n)
        for w in order:
            if row.sum() >= want:
                break
            row[w] = True
        adv[ev.step] = row
    return adv


def apply_straggle(straggle_schedule: Optional[np.ndarray],
                   plan: Optional[FaultPlan], num_workers: int,
                   n_steps: int) -> Optional[np.ndarray]:
    """Host-side schedule mutation for ``straggle`` events: a SUSTAINED
    per-worker drop — the targeted worker's rows stop arriving from the
    event step until recovery (``:d<dwell>`` steps later; without it, the
    end of the run — the spot/preemptible-instance shape). Unlike the
    one-shot crash kinds this rides the existing seeded straggler/present
    machinery: the drop is an *erasure at a known position* every step it
    lasts, which is exactly the fault surface the approx code family
    (coding/approx.py, ISSUE 8) decodes around with a bounded residual,
    and a scheduled straggler is never an accused worker (obs/forensics).

    ``straggle_schedule``: the seeded (rows, n) drop mask (True = absent)
    or None when cfg configured no stragglers — the mutation materializes
    a fresh all-False table then, sized ``n_steps + 1`` rows like
    rng.straggler_schedule. Passthrough (input returned untouched) when
    the plan has no straggle events."""
    if plan is None:
        return straggle_schedule
    events = plan.of_kind("straggle")
    if not events:
        return straggle_schedule
    if straggle_schedule is None:
        out = np.zeros((n_steps + 1, num_workers), dtype=bool)
    else:
        out = np.array(straggle_schedule, copy=True)
    for ev in events:
        if ev.step >= out.shape[0]:
            continue  # beyond the run's schedule table — inert
        hi = (out.shape[0] if ev.duration_s is None
              else min(out.shape[0], ev.step + int(ev.duration_s)))
        out[ev.step:hi, ev.worker] = True
    return out


# ---- host-side one-shot triggering ----------------------------------------


class HostFaultInjector:
    """Fires each host fault event exactly once, however many times the
    surrounding request is retried — so a supervised restart
    (resilience/supervisor.py) observes a clean re-execution, the way a
    transient real fault would behave. Inert (every method a cheap no-op)
    when built with ``plan=None``."""

    def __init__(self, plan: Optional[FaultPlan]):
        self._plan = plan
        self._fired: set = set()

    @property
    def active(self) -> bool:
        return self._plan is not None and bool(self._plan.events)

    def _fire(self, kinds, lo: int, hi: Optional[int] = None):
        """First unfired event of ``kinds`` with step in [lo, hi] (hi
        defaults to lo), marked fired."""
        if self._plan is None:
            return None
        hi = lo if hi is None else hi
        for ev in self._plan.of_kind(*kinds):
            key = (ev.kind, ev.step, ev.worker)
            if key not in self._fired and lo <= ev.step <= hi:
                self._fired.add(key)
                return ev
        return None

    def wrap_step_fn(self, fn):
        """Wrap a per-step host data fn (``fn(step) -> x``) so prefetch
        fault events fire when their step's data is first requested."""
        if not self.active:
            return fn

        def wrapped(step):
            self._maybe_prefetch_fault(step, step)
            return fn(step)

        return wrapped

    def wrap_range_fn(self, fn):
        """Wrap a chunk-range host data fn (``fn(start, k) -> x``) so
        prefetch fault events fire when the chunk containing their step is
        first requested."""
        if not self.active:
            return fn

        def wrapped(start, k):
            self._maybe_prefetch_fault(start, start + k - 1)
            return fn(start, k)

        return wrapped

    def _maybe_prefetch_fault(self, lo: int, hi: int) -> None:
        ev = self._fire(("prefetch_crash", "prefetch_hang"), lo, hi)
        if ev is None:
            return
        if ev.kind == "prefetch_crash":
            raise InjectedFaultError(
                f"injected prefetch_crash at step {ev.step} "
                f"(fault plan event)"
            )
        import time

        time.sleep(30.0 if ev.duration_s is None else ev.duration_s)

    def sigterm_due(self, end_step: int) -> bool:
        """True once, when a sigterm event's step has been reached — the
        loop then raises the real signal in-process so the registered
        GracefulStop handler (resilience/supervisor.py) runs the genuine
        preemption path."""
        return self._fire(("sigterm",), 1, end_step) is not None


NULL_INJECTOR = HostFaultInjector(None)
