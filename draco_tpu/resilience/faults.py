"""Deterministic fault injection — the chaos counterpart of ``attacks.py``.

The adversary schedules (draco_tpu/rng.py) make Byzantine behavior a seeded,
replayable experiment input; this module extends the same discipline to the
faults DRACO's code contract does NOT model (ISSUE 6): non-finite gradients
from faulty-but-honest workers, corruption past the s budget, dead or hung
prefetch threads, and SIGTERM mid-run. A :class:`FaultPlan` is parsed from
``cfg.fault_spec`` — a comma-separated list of ``kind@step`` events — so the
same plan replays bit-for-bit across runs, regimes (eager vs chunked) and
processes, which is what lets ``tools/chaos_run.py`` classify each fault
class as *masked* (final state bitwise-equal to a fault-free run) or
*gracefully degraded* (named error / resumable checkpoint / correct
terminal heartbeat state) instead of "something happened".

Event grammar (``FaultPlan.parse``)::

    kind@step[-end][:w<worker>][:d<seconds>][:every<k>]

    nan_grad@5          worker (seeded draw) emits a NaN gradient at step 5
    inf_grad@5:w2       worker 2 emits an Inf gradient at step 5
    over_budget@7       step 7's adversary row is pushed to s+1 live
                        adversaries (beyond the code's locator budget)
    adversary@5:w2      worker 2 is a LIVE adversary at step 5 (within the
                        code budget — the schedule row is set, the step's
                        cfg.err_mode attack fires through the normal
                        injection path); the declarative time-varying-
                        adversary knob the autopilot scenarios use
    adversary@5-40:w2   ... a sustained adversary EPISODE (steps 5..40)
    drift_grad@5-12     every worker's gradient is scaled by 2^-20 during
                        the window — a finite numerics-drift injection
                        (the whole wire's dynamic range drops a full
                        histogram band, shifting the exponent histogram
                        the ``numerics_drift`` incident detector watches,
                        while staying far from f32/int8-scale underflow;
                        ISSUE 15's autopilot wire_widen chaos cell)
    straggle@5:w3       worker 3 drops (sustained) from step 5 to the end
                        of the run — the heterogeneous-fleet / preempted-
                        worker fault the approx code family (ISSUE 8)
                        absorbs as scheduled erasures, NOT a one-shot
                        crash: the worker's rows simply stop arriving
    straggle@5:w3:d4    ... and recovers after 4 steps (absent 5..8)
    straggle@26-44:w5   ... absent exactly during the window (26..44)
    straggle@20-60:w3:d4:every10
                        CHURN: a recurring episode — a 4-step drop
                        starting at every 10th step of the window
                        (absent 20-23, 30-33, 40-43, 50-53, 60-63)
    prefetch_crash@5    the prefetcher host fn raises InjectedFaultError
                        the first time step 5's data is requested
    prefetch_hang@5:d6  ... sleeps 6 s instead (a stalled worker thread)
    sigterm@5           SIGTERM is raised in-process once step 5 completes
                        (a SECOND due sigterm event while the stop is
                        pending escalates — supervisor.ImmediateStopError)
    ckpt_corrupt@8      consumed by tools/chaos_run.py: flip bytes in the
    ckpt_truncate@8     step-8 checkpoint / truncate it, then resume

Windowed/recurring forms (``@a-b`` + ``:every<k>``) make time-varying
scenarios *declarative*: an event occurs at steps a, a+k, ..., ≤ b
(``:every`` requires a window; a bare ``@a-b`` recurs every step). Every
occurrence behaves exactly like a point event of its kind; host kinds
fire once per occurrence.

In-graph kinds are applied with the same branch-free ``jnp.where`` masking
as ``attacks.inject_plain`` — the fault is part of the compiled program
(config-static: an empty plan compiles the exact unfaulted program, and a
given plan compiles once; no steady-state retraces). Host kinds fire
one-shot through :class:`HostFaultInjector` so a supervised retry
(resilience/supervisor.py) re-executes the request cleanly — exactly how a
transient real-world fault behaves.
"""

from __future__ import annotations

import dataclasses
import functools
import re
from typing import Optional, Tuple

import numpy as np

# in-graph kinds corrupt the step's compiled inputs; schedule kinds mutate
# the seeded host schedules before upload (over_budget → adversary rows,
# straggle → straggler/present rows); host kinds fire in the host loop /
# prefetcher; ckpt kinds are consumed by tools/chaos_run.py
INGRAPH_KINDS = ("nan_grad", "inf_grad", "drift_grad")

# drift_grad's multiplicative payload: 2^-20 moves gradient-scale values
# (~1e-2) down ~6 decades — more than one full exponent-histogram band
# (obs/numerics.EXP_EDGES are 8-16 bins wide), so the numerics_drift
# detector's TV-shift signal goes loud, while every derived quantity
# (int8 per-block scales, squared energies in the decode health) stays in
# the f32 normal range: the injection perturbs NUMERICS, never
# finiteness or decode exactness
DRIFT_GRAD_SCALE = 2.0 ** -20
SCHEDULE_KINDS = ("over_budget", "straggle", "adversary")
HOST_KINDS = ("prefetch_crash", "prefetch_hang", "sigterm")
CKPT_KINDS = ("ckpt_corrupt", "ckpt_truncate")
FAULT_KINDS = INGRAPH_KINDS + SCHEDULE_KINDS + HOST_KINDS + CKPT_KINDS

# kinds whose :d payload is an integer STEP count (dwell), not seconds
_STEP_DWELL_KINDS = ("straggle", "adversary")
# kinds whose target worker is drawn from the seeded stream when no :w
# (drift_grad is fleet-wide — no victim to draw)
_DRAWN_WORKER_KINDS = ("nan_grad", "inf_grad", "straggle", "adversary")

_EVENT_RE = re.compile(r"^(?P<kind>[a-z_]+)@(?P<step>\d+)"
                       r"(?:-(?P<hi>\d+))?"
                       r"(?::w(?P<worker>\d+))?(?::d(?P<dur>[\d.]+))?"
                       r"(?::every(?P<every>\d+))?$")


class InjectedFaultError(RuntimeError):
    """The named error a ``prefetch_crash`` event raises — distinguishable
    from any organic failure, so chaos tests can assert the supervision
    path masked exactly the injected fault and nothing else."""


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    kind: str
    step: int  # 1-based training step the event (window) starts at
    worker: Optional[int] = None  # in-graph/straggle/adversary target row
    # ``:d<n>`` payload. prefetch_hang: seconds the worker thread sleeps
    # (None → 30 s). straggle/adversary: dwell in STEPS per occurrence
    # (None → sustained to the end of the run / a single step).
    duration_s: Optional[float] = None
    # window end (``@a-b``; None = the point event a) and recurrence
    # stride within it (``:every<k>``; 1 = every step of the window)
    step_hi: Optional[int] = None
    every: int = 1
    # position in the parsed spec — keys the one-shot host firing and the
    # seeded worker draw; excluded from equality so a round-tripped spec
    # (with blanks dropped) still compares equal
    index: int = dataclasses.field(default=0, compare=False)

    @property
    def last_step(self) -> int:
        return self.step if self.step_hi is None else self.step_hi

    def occurrences(self, lo: int, hi: int):
        """Occurrence steps within [lo, hi] — a, a+every, ..., <= b."""
        first = self.step
        if lo > first:
            # first occurrence at or after lo on the event's stride grid
            first += ((lo - self.step + self.every - 1)
                      // self.every) * self.every
        return range(first, min(self.last_step, hi) + 1, self.every)

    def occurs_at(self, step: int) -> bool:
        return (self.step <= step <= self.last_step
                and (step - self.step) % self.every == 0)

    def spec(self) -> str:
        """The event's canonical spec token — ``FaultPlan.parse`` of it
        reproduces this event (worker resolved, so the seeded draw is
        pinned explicit on the way out)."""
        tok = f"{self.kind}@{self.step}"
        if self.step_hi is not None:
            tok += f"-{self.step_hi}"
        if self.worker is not None:
            tok += f":w{self.worker}"
        if self.duration_s is not None:
            d = self.duration_s
            tok += f":d{int(d) if float(d).is_integer() else d}"
        if self.every != 1:
            tok += f":every{self.every}"
        return tok


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """An immutable, seed-deterministic set of fault events."""

    events: Tuple[FaultEvent, ...]
    seed: int
    num_workers: int

    @classmethod
    def parse(cls, spec: str, seed: int, num_workers: int) -> "FaultPlan":
        events = []
        for i, tok in enumerate(t.strip() for t in spec.split(",")):
            if not tok:
                continue
            m = _EVENT_RE.match(tok)
            if not m:
                raise ValueError(
                    f"fault_spec event {tok!r} does not match "
                    f"'kind@step[-end][:w<worker>][:d<seconds>]"
                    f"[:every<k>]'"
                )
            kind, step = m.group("kind"), int(m.group("step"))
            if kind not in FAULT_KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r}; known: "
                    f"{'|'.join(FAULT_KINDS)}"
                )
            if step < 1:
                raise ValueError(f"fault step must be >= 1 in {tok!r}")
            hi = m.group("hi")
            if hi is not None:
                hi = int(hi)
                if hi < step:
                    raise ValueError(
                        f"fault window end {hi} precedes start {step} in "
                        f"{tok!r}"
                    )
                if kind in CKPT_KINDS:
                    raise ValueError(
                        f"{kind} targets one checkpoint; a window makes "
                        f"no sense in {tok!r}"
                    )
            every = m.group("every")
            if every is not None:
                every = int(every)
                if every < 1:
                    raise ValueError(f"every must be >= 1 in {tok!r}")
                if hi is None:
                    raise ValueError(
                        f"':every' without a step window 'a-b' is inert "
                        f"in {tok!r} — recurrence needs a window to recur "
                        f"over"
                    )
            worker = m.group("worker")
            if worker is not None:
                worker = int(worker)
                if worker >= num_workers:
                    raise ValueError(
                        f"fault worker {worker} out of range "
                        f"(num_workers={num_workers}) in {tok!r}"
                    )
            elif kind in _DRAWN_WORKER_KINDS:
                # seeded per-event draw — the same "every participant can
                # recompute it" property as rng.adversary_schedule
                r = np.random.RandomState((seed ^ 0x4641554C) + 7919 * i)
                worker = int(r.randint(num_workers))
            dur = m.group("dur")
            if dur is not None and kind in _STEP_DWELL_KINDS \
                    and float(dur) != int(float(dur)):
                # :d is float SECONDS for host kinds but integer STEPS for
                # straggle/adversary — reject rather than silently floor
                raise ValueError(
                    f"{kind} dwell is a whole number of steps, got "
                    f"d{dur} in {tok!r}"
                )
            events.append(FaultEvent(
                kind=kind, step=step, worker=worker,
                duration_s=float(dur) if dur is not None else None,
                step_hi=hi, every=every or 1, index=i,
            ))
        return cls(events=tuple(events), seed=seed, num_workers=num_workers)

    def spec(self) -> str:
        """Canonical round-trippable spec: ``FaultPlan.parse(plan.spec(),
        seed, n) == plan`` (workers pinned explicit, blanks dropped)."""
        return ",".join(ev.spec() for ev in self.events)

    def of_kind(self, *kinds: str) -> Tuple[FaultEvent, ...]:
        return tuple(e for e in self.events if e.kind in kinds)

    @property
    def ingraph_events(self) -> Tuple[FaultEvent, ...]:
        return self.of_kind(*INGRAPH_KINDS)


@functools.lru_cache(maxsize=64)
def _cached_plan(spec: str, seed: int, num_workers: int) -> FaultPlan:
    return FaultPlan.parse(spec, seed, num_workers)


def plan_from_cfg(cfg) -> Optional[FaultPlan]:
    """The cfg's parsed plan, or None when no faults are configured (the
    common case — every consumer below is an exact no-op then)."""
    if not getattr(cfg, "fault_spec", ""):
        return None
    return _cached_plan(cfg.fault_spec, cfg.seed, cfg.num_workers)


# ---- in-graph injection ----------------------------------------------------


def corrupt_grads(grads, cfg, step):
    """Branch-free NaN/Inf injection into the (n, ...) per-worker gradient
    stack at the plan's in-graph events — IDENTITY (no added ops, no graph
    change) when the plan has none. ``step`` may be a traced scalar (the
    scanned drivers feed it per-iteration), so the comparison runs in-graph
    against the events' tiny static step/worker vectors: the same masked
    ``jnp.where`` discipline as attacks.inject_plain, and no retrace ever
    (the plan is config-static)."""
    plan = plan_from_cfg(cfg)
    if plan is None or not plan.ingraph_events or step is None:
        return grads
    import jax.numpy as jnp

    n = grads.shape[0]
    mask = jnp.zeros((n,), bool)
    payload = jnp.zeros((n,), grads.dtype)
    for ev in plan.ingraph_events:
        s = jnp.asarray(step, jnp.int32)
        if ev.step_hi is None:
            hit = jnp.asarray(ev.step, jnp.int32) == s
        else:
            # windowed/recurring form: occurrence iff inside [a, b] on the
            # event's stride grid — still branch-free, still config-static
            hit = ((s >= ev.step) & (s <= ev.step_hi)
                   & ((s - ev.step) % ev.every == 0))
        if ev.kind == "drift_grad":
            # fleet-wide multiplicative drift (no victim worker): the
            # whole wire's dynamic range collapses during the window
            grads = grads * jnp.where(
                hit, jnp.asarray(DRIFT_GRAD_SCALE, grads.dtype),
                jnp.asarray(1.0, grads.dtype))
            continue
        row = jnp.arange(n) == ev.worker
        mask = mask | (hit & row)
        val = jnp.nan if ev.kind == "nan_grad" else jnp.inf
        payload = jnp.where(hit & row, jnp.asarray(val, grads.dtype),
                            payload)
    shape = (n,) + (1,) * (grads.ndim - 1)
    return jnp.where(mask.reshape(shape), payload.reshape(shape), grads)


def apply_over_budget(adv_schedule: np.ndarray, plan: Optional[FaultPlan],
                      worker_fail: int) -> np.ndarray:
    """Host-side schedule mutation for ``over_budget`` events: the targeted
    steps' adversary rows gain seeded extra workers until s+1 are live —
    one corruption past the code's locator budget, the regime where exact
    recovery is impossible and the guard (resilience/guards.py) is the only
    thing standing between a silently poisoned update and a skipped one.
    Returns the (possibly copied) schedule; the input is never mutated."""
    if plan is None:
        return adv_schedule
    events = plan.of_kind("over_budget")
    if not events:
        return adv_schedule
    adv = np.array(adv_schedule, copy=True)
    n = adv.shape[1]
    want = min(worker_fail + 1, n)
    for ev in events:
        for o in ev.occurrences(1, adv.shape[0] - 1):
            row = adv[o]
            r = np.random.RandomState((plan.seed ^ 0x0B0D6E7) + o)
            order = r.permutation(n)
            for w in order:
                if row.sum() >= want:
                    break
                row[w] = True
            adv[o] = row
    return adv


def apply_adversary(adv_schedule: np.ndarray,
                    plan: Optional[FaultPlan]) -> np.ndarray:
    """Host-side schedule mutation for ``adversary`` events: the targeted
    worker's row goes live-adversarial at every occurrence (for ``:d``
    dwell steps each — default 1), WITHIN the code budget: this is the
    declarative time-varying-adversary knob (an attack EPISODE a fleet
    actually sees), not the beyond-budget ``over_budget`` stressor. The
    step's cfg.err_mode attack then fires through the exact same masked
    injection path as the seeded schedule. Returns the (possibly copied)
    schedule; the input is never mutated."""
    if plan is None:
        return adv_schedule
    events = plan.of_kind("adversary")
    if not events:
        return adv_schedule
    adv = np.array(adv_schedule, copy=True)
    for ev in events:
        dwell = 1 if ev.duration_s is None else int(ev.duration_s)
        for o in ev.occurrences(1, adv.shape[0] - 1):
            adv[o:min(o + dwell, adv.shape[0]), ev.worker] = True
    return adv


def apply_straggle(straggle_schedule: Optional[np.ndarray],
                   plan: Optional[FaultPlan], num_workers: int,
                   n_steps: int) -> Optional[np.ndarray]:
    """Host-side schedule mutation for ``straggle`` events: a SUSTAINED
    per-worker drop — the targeted worker's rows stop arriving from the
    event step until recovery (``:d<dwell>`` steps later; without it, the
    end of the run — the spot/preemptible-instance shape). Unlike the
    one-shot crash kinds this rides the existing seeded straggler/present
    machinery: the drop is an *erasure at a known position* every step it
    lasts, which is exactly the fault surface the approx code family
    (coding/approx.py, ISSUE 8) decodes around with a bounded residual,
    and a scheduled straggler is never an accused worker (obs/forensics).

    ``straggle_schedule``: the seeded (rows, n) drop mask (True = absent)
    or None when cfg configured no stragglers — the mutation materializes
    a fresh all-False table then, sized ``n_steps + 1`` rows like
    rng.straggler_schedule. Passthrough (input returned untouched) when
    the plan has no straggle events."""
    if plan is None:
        return straggle_schedule
    events = plan.of_kind("straggle")
    if not events:
        return straggle_schedule
    if straggle_schedule is None:
        out = np.zeros((n_steps + 1, num_workers), dtype=bool)
    else:
        out = np.array(straggle_schedule, copy=True)
    for ev in events:
        for o in ev.occurrences(1, out.shape[0] - 1):
            if ev.duration_s is not None:
                hi = min(out.shape[0], o + int(ev.duration_s))
            elif ev.step_hi is not None:
                # windowed form without :d — absent exactly DURING the
                # window (each occurrence covers its own step), recovering
                # at window end; only the point form means "to the end of
                # the run" (the spot-instance shape)
                hi = o + 1
            else:
                hi = out.shape[0]
            out[o:hi, ev.worker] = True
    return out


# ---- host-side one-shot triggering ----------------------------------------


class HostFaultInjector:
    """Fires each host fault event exactly once, however many times the
    surrounding request is retried — so a supervised restart
    (resilience/supervisor.py) observes a clean re-execution, the way a
    transient real fault would behave. Inert (every method a cheap no-op)
    when built with ``plan=None``."""

    def __init__(self, plan: Optional[FaultPlan]):
        self._plan = plan
        self._fired: set = set()

    @property
    def active(self) -> bool:
        return self._plan is not None and bool(self._plan.events)

    def _fire(self, kinds, lo: int, hi: Optional[int] = None):
        """First unfired OCCURRENCE of an event of ``kinds`` within
        [lo, hi] (hi defaults to lo), marked fired. Keyed by (event index,
        occurrence step): recurring events fire once per occurrence, and
        two identical point events (e.g. ``sigterm@5,sigterm@5`` — the
        pinned escalation sequence) each fire."""
        if self._plan is None:
            return None
        hi = lo if hi is None else hi
        for ev in self._plan.of_kind(*kinds):
            for o in ev.occurrences(lo, hi):
                key = (ev.index, o)
                if key not in self._fired:
                    self._fired.add(key)
                    return ev
        return None

    def wrap_step_fn(self, fn):
        """Wrap a per-step host data fn (``fn(step) -> x``) so prefetch
        fault events fire when their step's data is first requested."""
        if not self.active:
            return fn

        def wrapped(step):
            self._maybe_prefetch_fault(step, step)
            return fn(step)

        return wrapped

    def wrap_range_fn(self, fn):
        """Wrap a chunk-range host data fn (``fn(start, k) -> x``) so
        prefetch fault events fire when the chunk containing their step is
        first requested."""
        if not self.active:
            return fn

        def wrapped(start, k):
            self._maybe_prefetch_fault(start, start + k - 1)
            return fn(start, k)

        return wrapped

    def _maybe_prefetch_fault(self, lo: int, hi: int) -> None:
        ev = self._fire(("prefetch_crash", "prefetch_hang"), lo, hi)
        if ev is None:
            return
        if ev.kind == "prefetch_crash":
            raise InjectedFaultError(
                f"injected prefetch_crash at step {ev.step} "
                f"(fault plan event)"
            )
        import time

        time.sleep(30.0 if ev.duration_s is None else ev.duration_s)

    def sigterm_due(self, end_step: int) -> bool:
        """True once, when a sigterm event's step has been reached — the
        loop then raises the real signal in-process so the registered
        GracefulStop handler (resilience/supervisor.py) runs the genuine
        preemption path."""
        return self._fire(("sigterm",), 1, end_step) is not None


NULL_INJECTOR = HostFaultInjector(None)
