"""Host-side graceful degradation: prefetcher supervision, checkpoint
walk-back, and preemption-safe stop.

The in-graph guard (resilience/guards.py) covers the faults that reach the
compiled program; this module covers the host half of the resilience layer
(ISSUE 6) — the places a production run actually dies:

  SupervisedPrefetcher    a prefetcher worker exception or stall abandons
                          the broken instance and rebuilds it (exponential
                          backoff, bounded restarts) so a transient fault
                          re-executes the same deterministic request and
                          the run continues bit-for-bit; when restarts are
                          exhausted the ORIGINAL named error propagates.
  restore_with_walkback   resume never dies on one corrupt checkpoint:
                          walk back through older checkpoints until one
                          loads (CheckpointCorruptError rows are skipped
                          and reported). Walk-back needs something to walk
                          back TO — retain-last-N GC keeps the newest N by
                          step, not by integrity, so run with
                          keep_checkpoints >= 2 (or 0) where torn newest
                          checkpoints are a live concern.
  GracefulStop            SIGTERM/SIGINT request a stop instead of killing
                          the process mid-chunk: the loops check
                          ``stop.requested`` at chunk boundaries, snap a
                          boundary checkpoint, and write the terminal
                          ``status.json`` state ("preempted", resumable) —
                          which makes the chunk-boundary checkpoints the
                          preemption/elasticity mechanism ROADMAP item 1
                          calls for.
"""

from __future__ import annotations

import signal
import threading
import time
from typing import Any, Callable, Optional

from draco_tpu.obs.tracer import NULL_TRACER


class SupervisedPrefetcher:
    """Wraps any prefetcher (``get``/``depth``/``close``) built by
    ``factory`` with restart-on-failure supervision.

    A failed ``get`` abandons the instance (best-effort, never waiting on a
    hung worker), sleeps an exponentially growing backoff, rebuilds via
    ``factory`` and retries the SAME request — deterministic data sources
    (all of draco_tpu's are) make the retry bitwise-identical to an
    untroubled fetch, so a transient fault is fully masked. After
    ``restarts`` rebuilds the original exception propagates: bounded, never
    an infinite crash loop. ``restarts=0`` is a transparent passthrough."""

    def __init__(self, factory: Callable[[], Any], restarts: int = 2,
                 backoff_s: float = 0.05, tracer=NULL_TRACER):
        self._factory = factory
        self._restarts = max(int(restarts), 0)
        self._backoff_s = backoff_s
        self._tracer = tracer
        self._p = factory()
        self.restarts_used = 0

    @property
    def depth(self) -> int:
        return self._p.depth

    def get(self, *args, **kwargs):
        if self._p is None:  # rebuilt lazily after an exhausted-retry raise
            self._p = self._factory()
        delay = self._backoff_s
        for attempt in range(self._restarts + 1):
            try:
                return self._p.get(*args, **kwargs)
            except Exception as e:
                # the failing instance is ALWAYS abandoned — on the final
                # attempt too, so the caller's cleanup (close()) never
                # joins a worker known to be broken/hung
                self._abandon()
                if attempt == self._restarts:
                    raise
                self._tracer.instant(
                    "prefetch.restart",
                    error=f"{type(e).__name__}: {e}"[:200],
                    attempt=attempt + 1,
                )
                time.sleep(delay)
                delay *= 2
                self._p = self._factory()
                self.restarts_used += 1

    def stats(self) -> dict:
        """The supervision counters the heartbeat beat carries (and the
        incident engine's starvation detector consumes, ISSUE 13): how
        many times a prefetcher was abandoned + rebuilt this run."""
        return {"prefetch_restarts": self.restarts_used}

    def _abandon(self) -> None:
        """Drop the broken instance without ever blocking on it (a hung
        worker thread must not hang the supervisor too)."""
        p, self._p = self._p, None
        try:
            if hasattr(p, "abandon"):
                p.abandon()
            else:
                p.close()
        except Exception:
            pass

    def close(self) -> None:
        if self._p is not None:
            try:
                self._p.close()
            except Exception:
                pass


# ---- checkpoint walk-back --------------------------------------------------


def restore_with_walkback(train_dir: str, step: int, abstract_state,
                          loader=None):
    """Load the checkpoint at ``step`` (or the newest one when ``step ==
    -1``), walking back through older checkpoints past any that fail with
    :class:`~draco_tpu.utils.checkpoint.CheckpointCorruptError`.

    Returns ``(state, loaded_step, skipped)`` where ``skipped`` is a list of
    ``(step, error_str)`` for every corrupt checkpoint walked past — each is
    also printed here (one report site for both production loops; a corrupt
    newest checkpoint is a real event, just not a fatal one). Raises the
    LAST corruption error when nothing loads, or FileNotFoundError when the
    dir holds no checkpoints at all. Any non-corruption load failure
    propagates immediately: walk-back is for torn bytes, not for masking
    structural mismatches."""
    from draco_tpu.utils import checkpoint as ckpt

    load = loader or ckpt.load
    steps = ckpt.available_steps(train_dir)
    if step == -1:
        candidates = sorted(steps, reverse=True)
    else:
        candidates = [step] + sorted((s for s in steps if s < step),
                                     reverse=True)
    if not candidates:
        raise FileNotFoundError(
            f"no checkpoints in {train_dir!r} to restore from"
        )
    skipped = []
    last_err: Optional[Exception] = None
    for s in candidates:
        try:
            return load(train_dir, s, abstract_state), s, skipped
        except ckpt.CheckpointCorruptError as e:
            print(f"checkpoint walk-back: skipped corrupt step {s} ({e})",
                  flush=True)
            skipped.append((s, str(e)))
            last_err = e
    raise last_err


# ---- preemption-safe stop --------------------------------------------------


class ImmediateStopError(Exception):
    """A REPEAT SIGTERM/SIGINT while a graceful stop was already pending:
    the operator (or the platform's escalating kill sequence) is not
    willing to wait for the chunk boundary. Raised from the signal handler
    so it surfaces wherever the main thread currently is — mid-chunk, in a
    metric fetch, in an upload — and the production loops catch it to snap
    an IMMEDIATE resumable checkpoint (the newest dispatched state) and
    write the terminal ``preempted`` status, instead of finishing the
    chunk grid. A third signal falls through to the previously-installed
    handler (the handlers are restored before this raises), so a stuck
    escalation can still be killed the ordinary way."""


class GracefulStop:
    """Context manager converting SIGTERM/SIGINT into a cooperative stop
    request the training loops poll at chunk boundaries.

    Installs handlers on ``__enter__`` (main thread only — elsewhere, e.g.
    under a test runner thread, it degrades to an inert flag holder) and
    restores the previous handlers on ``__exit__``. A second signal while
    a stop is already pending ESCALATES: the previous handlers are
    restored and :class:`ImmediateStopError` is raised from the handler,
    which the loops turn into an immediate resumable checkpoint + terminal
    ``preempted`` status (no waiting for the chunk boundary); a third
    signal then hits the restored handler and kills the ordinary way."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = signals
        self._previous: dict = {}
        self.requested = False
        self.escalated = False
        self.signame: Optional[str] = None
        # the loop that honored the stop records where it snapped the
        # resumable checkpoint, for the terminal status.json
        self.stopped_step: Optional[int] = None

    def _handler(self, signum, frame):
        if self.requested:  # second signal: escalate to an immediate stop
            for sig, prev in self._previous.items():
                signal.signal(sig, prev)
            self._previous = {}
            self.escalated = True
            raise ImmediateStopError(
                f"second {signal.Signals(signum).name} while a graceful "
                f"stop was pending — immediate checkpoint requested")
        self.requested = True
        self.signame = signal.Signals(signum).name

    @property
    def installed(self) -> bool:
        """True when this instance's handlers are live (main-thread
        __enter__); False means deliver_signal degrades to the flag."""
        return bool(self._previous)

    def deliver_signal(self, sig=signal.SIGTERM) -> None:
        """Deliver ``sig`` through the REAL handler path when installed
        (the genuine preemption flow — what the fault plan's sigterm event
        uses), degrading to a direct stop request when handlers could not
        be installed (non-main-thread runners, e.g. under a test
        harness). The degraded path keeps the escalation semantics: a
        second delivery while a stop is pending raises
        :class:`ImmediateStopError` exactly like the live handler."""
        if self.installed:
            signal.raise_signal(sig)
        elif self.requested:
            self.escalated = True
            raise ImmediateStopError(
                f"second {signal.Signals(sig).name} while a graceful "
                f"stop was pending — immediate checkpoint requested")
        else:
            self.requested = True
            self.signame = signal.Signals(sig).name

    def __enter__(self) -> "GracefulStop":
        if threading.current_thread() is threading.main_thread():
            for sig in self._signals:
                self._previous[sig] = signal.signal(sig, self._handler)
        return self

    def __exit__(self, *exc):
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous = {}
        return False


def stop_requested(stop: Optional[GracefulStop], injector,
                   step: int) -> bool:
    """The one stop-poll both production loops share: fire EVERY pending
    fault-plan sigterm event due by ``step`` (delivered through the real
    handler path — a second due event while the first is pending escalates
    to :class:`ImmediateStopError`, the pinned SIGTERM→SIGTERM sequence),
    then report whether a graceful stop is pending. ``stop`` may be None
    (driver called without the resilience envelope)."""
    while injector.sigterm_due(step):
        if stop is None:
            break
        stop.deliver_signal(signal.SIGTERM)
    return stop is not None and stop.requested
