"""CIFAR ResNet-18/34/50/101/152 (reference: src/model_ops/resnet.py).

3×3 stem (no max-pool), stage widths 64/128/256/512, BasicBlock for 18/34 and
Bottleneck (expansion 4) for 50/101/152, 4×4 average pool before the
classifier — the standard CIFAR variant the reference uses.

BatchNorm policy (load-bearing for the coded paths, see SURVEY.md §7.4): the
reference never ships running statistics to the PS (src/worker/utils.py:46-48);
each worker keeps local stats and only *parameters* are aggregated. Here the
``batch_stats`` collection is vmapped per logical worker and never averaged;
training normalisation uses batch statistics, so two workers given the same
batch produce bitwise-identical gradients — which is what the repetition
vote and the cyclic decode rely on.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp


class BasicBlock(nn.Module):
    planes: int
    stride: int = 1
    dtype: Any = jnp.float32  # MXU compute dtype; params/stats stay float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = lambda: nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                    dtype=self.dtype)
        conv = lambda *a, **k: nn.Conv(*a, use_bias=False, dtype=self.dtype, **k)
        in_planes = x.shape[-1]
        out = conv(self.planes, (3, 3), strides=(self.stride, self.stride),
                   padding=((1, 1), (1, 1)))(x)
        out = nn.relu(norm()(out))
        out = conv(self.planes, (3, 3), padding=((1, 1), (1, 1)))(out)
        out = norm()(out)
        if self.stride != 1 or in_planes != self.planes:
            x = conv(self.planes, (1, 1), strides=(self.stride, self.stride))(x)
            x = norm()(x)
        return nn.relu(out + x)


class Bottleneck(nn.Module):
    planes: int
    stride: int = 1
    expansion: int = 4
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = lambda: nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                    dtype=self.dtype)
        conv = lambda *a, **k: nn.Conv(*a, use_bias=False, dtype=self.dtype, **k)
        in_planes = x.shape[-1]
        wide = self.planes * self.expansion
        out = conv(self.planes, (1, 1))(x)
        out = nn.relu(norm()(out))
        out = conv(self.planes, (3, 3), strides=(self.stride, self.stride),
                   padding=((1, 1), (1, 1)))(out)
        out = nn.relu(norm()(out))
        out = conv(wide, (1, 1))(out)
        out = norm()(out)
        if self.stride != 1 or in_planes != wide:
            x = conv(wide, (1, 1), strides=(self.stride, self.stride))(x)
            x = norm()(x)
        return nn.relu(out + x)


class ResNet(nn.Module):
    block: Callable
    num_blocks: Sequence[int]
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(64, (3, 3), padding=((1, 1), (1, 1)), use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.relu(nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                 dtype=self.dtype)(x))
        for stage, (planes, blocks) in enumerate(zip((64, 128, 256, 512), self.num_blocks)):
            for b in range(blocks):
                stride = 2 if (stage > 0 and b == 0) else 1
                x = self.block(planes, stride, dtype=self.dtype)(x, train=train)
        x = nn.avg_pool(x, (4, 4), strides=(4, 4))
        x = x.reshape((x.shape[0], -1))
        # classifier + logits in float32 (loss numerics)
        return nn.Dense(self.num_classes)(x.astype(jnp.float32))


def ResNet18(num_classes: int = 10, dtype: Any = jnp.float32):
    return ResNet(BasicBlock, (2, 2, 2, 2), num_classes, dtype)


def ResNet34(num_classes: int = 10, dtype: Any = jnp.float32):
    return ResNet(BasicBlock, (3, 4, 6, 3), num_classes, dtype)


def ResNet50(num_classes: int = 10, dtype: Any = jnp.float32):
    return ResNet(Bottleneck, (3, 4, 6, 3), num_classes, dtype)


def ResNet101(num_classes: int = 10, dtype: Any = jnp.float32):
    return ResNet(Bottleneck, (3, 4, 23, 3), num_classes, dtype)


def ResNet152(num_classes: int = 10, dtype: Any = jnp.float32):
    return ResNet(Bottleneck, (3, 8, 36, 3), num_classes, dtype)
