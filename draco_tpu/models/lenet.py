"""LeNet for MNIST (reference: src/model_ops/lenet.py:20-41).

conv(1→20, 5×5, VALID) → maxpool2 → relu → conv(20→50) → maxpool2 → relu →
fc(800→500) → fc(500→10). Note the reference applies relu *after* the pool;
kept as-is."""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class LeNet(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.float32  # MXU compute dtype; params stay float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(20, (5, 5), padding="VALID", dtype=self.dtype)(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(x)
        x = nn.Conv(50, (5, 5), padding="VALID", dtype=self.dtype)(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))  # (B, 4*4*50)
        x = nn.Dense(500, dtype=self.dtype)(x)
        x = nn.Dense(self.num_classes)(x.astype(jnp.float32))
        return x
