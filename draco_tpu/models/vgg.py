"""CIFAR VGG-11/13/16/19 with optional BatchNorm (reference: src/model_ops/vgg.py).

Feature configs A/B/D/E with 2×2 max-pools; classifier
dropout → 512 → relu → dropout → 512 → relu → 10.

Dropout determinism (TPU-native design decision): the reference seeds torch's
global RNG per group/epoch, which makes dropout *group*-deterministic for the
repetition code but leaves the cyclic path's per-batch gradients
worker-dependent (two workers computing the same batch draw different dropout
masks — decode there was only approximate). Here the dropout rng key is folded
from (step, batch-id) by the trainer, so any worker computing batch k draws
the same mask and both codes stay exactly decodable.
"""

from __future__ import annotations

from typing import Any, Sequence, Union

import flax.linen as nn
import jax.numpy as jnp

_CFG = {
    "A": (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    "B": (64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    "D": (64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
          512, 512, 512, "M"),
    "E": (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512, 512, 512,
          "M", 512, 512, 512, 512, "M"),
}


class VGG(nn.Module):
    cfg: Sequence[Union[int, str]]
    batch_norm: bool = False
    num_classes: int = 10
    dtype: Any = jnp.float32  # MXU compute dtype; params/stats stay float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        for v in self.cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(int(v), (3, 3), padding=((1, 1), (1, 1)),
                            dtype=self.dtype)(x)
                if self.batch_norm:
                    x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                     dtype=self.dtype)(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))  # (B, 512)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(512, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(512, dtype=self.dtype)(x))
        # logits in float32 (loss numerics)
        return nn.Dense(self.num_classes)(x.astype(jnp.float32))


def VGG11(num_classes: int = 10, dtype: Any = jnp.float32):
    return VGG(_CFG["A"], False, num_classes, dtype)


def VGG11_bn(num_classes: int = 10, dtype: Any = jnp.float32):
    return VGG(_CFG["A"], True, num_classes, dtype)


def VGG13(num_classes: int = 10, dtype: Any = jnp.float32):
    return VGG(_CFG["B"], False, num_classes, dtype)


def VGG13_bn(num_classes: int = 10, dtype: Any = jnp.float32):
    return VGG(_CFG["B"], True, num_classes, dtype)


def VGG16(num_classes: int = 10, dtype: Any = jnp.float32):
    return VGG(_CFG["D"], False, num_classes, dtype)


def VGG16_bn(num_classes: int = 10, dtype: Any = jnp.float32):
    return VGG(_CFG["D"], True, num_classes, dtype)


def VGG19(num_classes: int = 10, dtype: Any = jnp.float32):
    return VGG(_CFG["E"], False, num_classes, dtype)


def VGG19_bn(num_classes: int = 10, dtype: Any = jnp.float32):
    return VGG(_CFG["E"], True, num_classes, dtype)
