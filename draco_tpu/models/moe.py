"""Switch-style mixture-of-experts MLP — the expert-parallel workload.

Top-1 token routing with a fixed per-expert capacity (Switch Transformer
semantics): tokens pick their argmax expert, overflow beyond
``capacity_factor · N/E`` tokens per expert is dropped (the token passes
through the residual stream unchanged — standard Switch behaviour), and
dispatch/combine are dense one-hot einsums so the whole layer is one
fixed-shape jittable program (no data-dependent shapes; the TPU requirement
that shaped this framework's decode path too, SURVEY.md §7.1-3).

Expert weights are stacked on a leading E axis, which is what the
expert-parallel path shards over mesh axis ``ep``
(draco_tpu/parallel/ep_step.py): the per-expert FFN einsum is batched over
E, so GSPMD turns the E-sharding into an all-to-all-free local compute with
dispatch/combine resharding at the boundaries.

No reference counterpart (CNN-only zoo); part of the TPU build's scale-out
surface beyond parity.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoeMlp(nn.Module):
    dim: int
    experts: int
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        """x: (B, T, D) -> (B, T, D). Dropped (over-capacity) tokens return 0
        here and survive via the caller's residual connection."""
        b, t, d = x.shape
        e = self.experts
        hidden = self.mlp_ratio * d
        n_tok = b * t
        cap = max(int(self.capacity_factor * n_tok / e), 1)
        xf = x.reshape(n_tok, d)

        # router in f32 (softmax numerics); top-1 with index-order tie-break
        logits = nn.Dense(e, use_bias=False, name="router",
                          dtype=jnp.float32)(xf.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)  # (N, E)
        eidx = jnp.argmax(probs, axis=-1)  # (N,)
        gate = jnp.take_along_axis(probs, eidx[:, None], axis=-1)[:, 0]  # (N,)

        onehot = jax.nn.one_hot(eidx, e, dtype=jnp.float32)  # (N, E)
        # arrival-order position of each token within its expert's buffer
        pos = jnp.cumsum(onehot, axis=0) - 1.0  # (N, E)
        keep = (pos < cap) * onehot  # (N, E), 1 where routed AND in capacity
        # (N, E, C) one-hot dispatch/combine tensor
        dispatch = keep[:, :, None] * jax.nn.one_hot(
            pos.astype(jnp.int32), cap, dtype=jnp.float32
        )

        w1 = self.param(
            "w1", nn.initializers.lecun_normal(batch_axis=(0,)), (e, d, hidden)
        )
        b1 = self.param("b1", nn.initializers.zeros, (e, 1, hidden))
        w2 = self.param(
            "w2", nn.initializers.lecun_normal(batch_axis=(0,)), (e, hidden, d)
        )
        b2 = self.param("b2", nn.initializers.zeros, (e, 1, d))

        cd = self.dtype
        xe = jnp.einsum("nd,nec->ecd", xf.astype(jnp.float32), dispatch)
        h = jnp.einsum("ecd,edh->ech", xe.astype(cd), w1.astype(cd)) + b1.astype(cd)
        h = nn.gelu(h)
        ye = jnp.einsum("ech,ehd->ecd", h, w2.astype(cd)) + b2.astype(cd)
        yf = jnp.einsum("ecd,nec->nd", ye.astype(jnp.float32), dispatch)
        yf = yf * gate[:, None]  # straight-through top-1 gate (router trains)
        return yf.reshape(b, t, d).astype(x.dtype)
