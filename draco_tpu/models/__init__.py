"""Model zoo — Flax ports of the reference's model_ops/ architectures.

The reference carries two copies of every model: a plain nn.Module and a
"*Split" variant whose hand-rolled per-layer backward streams each gradient
over MPI as soon as it exists (reference: src/model_ops/resnet_split.py:431-623).
Under XLA the overlap the Split models bought is the compiler's job (async
collectives + latency hiding), so there is exactly one copy of each model here.
"""

from draco_tpu.models.fc import FC_NN
from draco_tpu.models.lenet import LeNet
from draco_tpu.models.resnet import (
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
from draco_tpu.models.transformer import TransformerLM
from draco_tpu.models.vgg import (
    VGG,
    VGG11,
    VGG11_bn,
    VGG13,
    VGG13_bn,
    VGG16,
    VGG16_bn,
    VGG19,
    VGG19_bn,
)

_REGISTRY = {
    "LeNet": LeNet,
    "FC": FC_NN,
    "ResNet18": ResNet18,
    "ResNet34": ResNet34,
    "ResNet50": ResNet50,
    "ResNet101": ResNet101,
    "ResNet152": ResNet152,
    "VGG11": VGG11,
    "VGG11_bn": VGG11_bn,
    "VGG13": VGG13,
    "VGG13_bn": VGG13_bn,
    "VGG16": VGG16,
    "VGG16_bn": VGG16_bn,
    "VGG19": VGG19,
    "VGG19_bn": VGG19_bn,
}


def build_model(name: str, num_classes: int = 10, dtype=None):
    """Name-based model construction (reference: build_model switches in
    baseline_master.py:30-47 / baseline_worker.py:37-50). ``dtype``: compute
    dtype for the conv/dense stacks ("bfloat16" rides the MXU at full rate;
    params, BN stats and logits stay float32)."""
    if name == "TransformerLM":
        raise ValueError(
            "TransformerLM is a token model and does not run on the image "
            "pipeline; the CLI routes it automatically, or construct it via "
            "draco_tpu.parallel.sp_step.build_sp_train_setup (all knobs) / "
            "draco_tpu.models.TransformerLM directly"
        )
    if name not in _REGISTRY:
        raise ValueError(f"unknown network: {name} (have {sorted(_REGISTRY)})")
    kwargs = {"num_classes": num_classes}
    if dtype is not None:
        import jax.numpy as jnp

        kwargs["dtype"] = jnp.dtype(dtype)
    return _REGISTRY[name](**kwargs)


def input_shape(dataset: str):
    """Per-dataset sample shape, NHWC."""
    d = dataset.lower()
    if "mnist" in d:
        return (28, 28, 1)
    if "cifar" in d:
        return (32, 32, 3)
    raise ValueError(f"unknown dataset: {dataset}")
