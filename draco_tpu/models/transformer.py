"""Decoder-only Transformer LM — the long-context workload of the TPU build.

The reference's zoo is CNN-only (src/model_ops/: LeNet/FC/ResNet/VGG —
SURVEY.md §2.1 row 14); this model adds the sequence dimension those models
lack, so the sequence-parallel axis (draco_tpu/parallel/) has a first-class
consumer. Attention is injectable: dense causal attention single-shard, ring
attention under sequence parallelism — the module code is identical in both
worlds, only ``attn_fn`` changes.

Design notes (TPU-first): pre-LN blocks, RoPE (positions arrive as an offset
so a sequence shard knows its global coordinates), GELU MLP, weight-tied
logits. All matmuls are batched over (B·T) and land on the MXU.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

AttnFn = Callable[..., jnp.ndarray]  # (q, k, v) -> o, all (B, T, H, Dh)


def rope(x: jnp.ndarray, positions: jnp.ndarray, base: float = 10000.0) -> jnp.ndarray:
    """Rotary embedding. x: (B, T, H, Dh), positions: (T,) global coords."""
    dh = x.shape[-1]
    half = dh // 2
    freqs = 1.0 / (base ** (np.arange(0, half) / half))
    angles = positions[:, None] * freqs[None, :]  # (T, half)
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)


class Block(nn.Module):
    dim: int
    heads: int
    mlp_ratio: int = 4
    attn_fn: Optional[AttnFn] = None
    experts: int = 0  # >0 replaces the dense MLP with a Switch MoE (moe.py)
    dtype: Any = jnp.float32  # MXU compute dtype; params stay float32

    @nn.compact
    def __call__(self, x, positions, train: bool):
        return self._body(x, positions, train)

    def _body(self, x, positions, train: bool):
        b, t, _ = x.shape
        dh = self.dim // self.heads
        h = nn.LayerNorm(use_bias=False, dtype=self.dtype)(x)
        qkv = nn.Dense(3 * self.dim, use_bias=False, name="qkv", dtype=self.dtype)(h)
        q, k, v = jnp.split(qkv.reshape(b, t, 3 * self.heads, dh), 3, axis=2)
        # attention math (rope, softmax accumulators) in float32 for the
        # ring's log-sum-exp stability; projections back in compute dtype
        q = rope(q.astype(jnp.float32), positions)
        k = rope(k.astype(jnp.float32), positions)
        v = v.astype(jnp.float32)
        attn = self.attn_fn
        if attn is None:
            from draco_tpu.parallel.ring_attention import dense_attention

            off = positions[0]
            attn = lambda q, k, v: dense_attention(q, k, v, q_offset=off, k_offset=off)
        o = attn(q, k, v).reshape(b, t, self.dim)
        x = x + nn.Dense(self.dim, use_bias=False, name="proj", dtype=self.dtype)(o)
        h = nn.LayerNorm(use_bias=False, dtype=self.dtype)(x)
        if self.experts > 0:
            from draco_tpu.models.moe import MoeMlp

            x = x + MoeMlp(self.dim, self.experts, self.mlp_ratio,
                           dtype=self.dtype, name="moe")(h)
        else:
            h = nn.Dense(self.mlp_ratio * self.dim, name="mlp_in", dtype=self.dtype)(h)
            h = nn.gelu(h)
            x = x + nn.Dense(self.dim, name="mlp_out", dtype=self.dtype)(h)
        return x


class BlockScan(Block):
    """``Block`` with the ``(carry, per-step-output)`` return convention
    ``nn.scan`` requires. Same fields, same math, same parameter names —
    only the return shape differs, so stacking the unrolled blocks' params
    along a leading layer axis reproduces the scanned model exactly."""

    @nn.compact
    def __call__(self, x, positions, train: bool):
        return self._body(x, positions, train), None


class TransformerLM(nn.Module):
    """Returns next-token logits (B, T, vocab).

    ``pos_offset``: global position of this sequence shard's first token —
    0 single-shard; ``axis_index(sp) * T_local`` under sequence parallelism.
    """

    vocab: int = 256
    dim: int = 128
    heads: int = 4
    layers: int = 2
    attn_fn: Optional[AttnFn] = None
    experts: int = 0  # >0: every block's MLP becomes a Switch MoE
    dtype: Any = jnp.float32
    # per-block rematerialisation: drop each block's activations and
    # recompute them in backward (jax.checkpoint) — peak activation memory
    # becomes one block's instead of `layers` blocks', buying long sequences
    # / big batches for FLOPs. Collectives inside a block (ring attention's
    # ppermute hops) replay in the recompute, which is SPMD-safe.
    remat: bool = False
    # compile the layer stack as ONE nn.scan over stacked block weights
    # instead of `layers` unrolled copies of the block program. Identical
    # math (test_transformer_scan.py proves output parity against the
    # unrolled model with restacked params); the XLA program shrinks by
    # ~`layers`×, which is what keeps very deep/big configs under
    # compile-time/service ceilings. Parameter tree changes shape (one
    # "blocks" subtree with a leading layer axis instead of block0..N-1),
    # so checkpoints are not interchangeable with the unrolled layout.
    scan_layers: bool = False

    @nn.compact
    def __call__(self, tokens, pos_offset=0, train: bool = True):
        emb = nn.Embed(self.vocab, self.dim, name="embed")
        x = emb(tokens).astype(self.dtype)
        positions = pos_offset + jnp.arange(tokens.shape[1])
        # static_argnums counts self as 0 (flax subtracts 1 internally), so
        # the train flag of __call__(self, x, positions, train) is 3
        if self.scan_layers:
            # prevent_cse is unnecessary inside nn.scan (flax checkpoint
            # docs — same discipline as pp_step._PipeBlock) and would put a
            # barrier in every scanned body
            cls = (nn.remat(BlockScan, static_argnums=(3,),
                            prevent_cse=False)
                   if self.remat else BlockScan)
            stack = nn.scan(
                cls,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, nn.broadcast),  # positions, train
                length=self.layers,
            )(self.dim, self.heads, attn_fn=self.attn_fn,
              experts=self.experts, dtype=self.dtype, name="blocks")
            x, _ = stack(x, positions, train)
        else:
            blk_cls = (nn.remat(Block, static_argnums=(3,))
                       if self.remat else Block)
            for i in range(self.layers):
                x = blk_cls(self.dim, self.heads, attn_fn=self.attn_fn,
                            experts=self.experts, dtype=self.dtype,
                            name=f"block{i}")(x, positions, train)
        x = nn.LayerNorm(use_bias=False, name="final_ln")(x)
        # logits in float32 (loss numerics)
        return emb.attend(x.astype(jnp.float32))
