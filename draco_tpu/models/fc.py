"""Fully-connected MNIST net (reference: src/model_ops/fc_nn.py:21-39).

784 → 800 → relu → 500 → relu → 10 → sigmoid. The trailing sigmoid before
cross-entropy is a reference quirk preserved for parity (the canonical
run_pytorch.sh config trains exactly this model)."""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class FC_NN(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.float32  # MXU compute dtype; params stay float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        x = nn.Dense(800, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(500, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes)(x.astype(jnp.float32))
        x = nn.sigmoid(x)
        return x
