from draco_tpu.training.step import TrainState, build_train_setup  # noqa: F401
from draco_tpu.training.trainer import Trainer  # noqa: F401
