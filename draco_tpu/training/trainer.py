"""Training loop — host-side orchestration around the jitted SPMD step.

Replaces the reference's per-role hot loops (SyncReplicasMaster_NN.start /
DistributedWorker.train and their coded variants, SURVEY.md §3) with one loop:
build batches (deterministic, approach-specific), device_put them sharded over
the worker axis, call the jitted step, emit metrics with the reference's
segment names, checkpoint every eval_freq steps.

Two execution regimes, selected by ``cfg.steps_per_call``:

* K=1 (default): the eager per-step loop — one dispatch, one metrics fetch,
  one ``block_until_ready`` per step. Honest on CPU (PERF.md §4: XLA:CPU
  serializes conv thunks inside scan bodies) and the bitwise reference for
  the chunked path.
* K>1: the scan-chunked loop — ``train_many`` fuses K full coded steps into
  one device program (training/step.py); the host runs a two-deep pipeline
  (assemble + device_put chunk i+1 while chunk i executes), metrics are
  deferred (K, m) device blocks materialized only at log/eval/checkpoint
  boundaries, and there is NO host sync in steady state. Eval/checkpoint
  cadence snaps to chunk boundaries via explicit remainder chunks, so
  ``max_steps`` need not divide by K. This is what hides the ~70 ms/dispatch
  RTT of remote backends (PERF.md §0) behind useful device work.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from draco_tpu import rng as drng
from draco_tpu.config import TrainConfig
from draco_tpu.data import batching
from draco_tpu.data.datasets import Dataset, load_dataset
from draco_tpu.data.prefetch import BatchPrefetcher, ChunkPrefetcher
from draco_tpu.obs import (
    RunHeartbeat,
    make_compile_watch,
    make_tracer,
    profiler_window,
)
from draco_tpu.obs.forensics import record_value
from draco_tpu.resilience import faults as faults_mod
from draco_tpu.resilience.supervisor import (
    GracefulStop,
    ImmediateStopError,
    SupervisedPrefetcher,
    restore_with_walkback,
)
from draco_tpu.runtime import WORKER_AXIS, make_mesh, put_global
from draco_tpu.training.step import build_train_setup
from draco_tpu.utils import checkpoint as ckpt
from draco_tpu.utils.metrics import MetricWriter, Segments


class Trainer:
    def __init__(self, cfg: TrainConfig, mesh=None, dataset: Optional[Dataset] = None,
                 quiet: bool = False):
        self.cfg = cfg.validate()
        self.mesh = mesh if mesh is not None else make_mesh(cfg.num_workers)
        self.ds = dataset if dataset is not None else load_dataset(cfg.dataset, cfg.data_dir)
        self.setup = build_train_setup(cfg, self.mesh, dataset_name=self.ds.name)
        self.state = self.setup.state
        # on multi-host, only process 0 emits metrics (checkpoint saves stay
        # collective — every process contributes its addressable shards)
        self._is_main = jax.process_index() == 0
        self.writer = MetricWriter(cfg.train_dir if self._is_main else None,
                                   quiet=quiet or not self._is_main)
        # telemetry (draco_tpu/obs): host span trace when cfg.trace_dir is
        # set, status.json heartbeat whenever there is a train_dir — both
        # no-ops off the metrics-emitting process, and the tracer is the
        # allocation-free NULL_TRACER when disabled
        self.tracer = make_tracer(cfg.trace_dir, self._is_main)
        # num_workers keys the heartbeat's per-worker accusation ledger
        # (obs/forensics.AccusationLedger) — it folds the packed forensics
        # mask columns at the same observer hook, zero extra fetches.
        # The incident engine (obs/incidents.py, ISSUE 13) rides the same
        # hook + the beat when cfg.incident_watch is on: host-side only,
        # bitwise-transparent to training
        from draco_tpu.obs import incidents as incidents_mod

        self.heartbeat = RunHeartbeat(
            cfg.train_dir or None, enabled=self._is_main,
            num_workers=cfg.num_workers,
            incidents=incidents_mod.make_engine(cfg, self._is_main),
            job_name=getattr(cfg, "job_name", "") or None)
        # static logical wire-bytes ledger (obs/numerics.wire_ledger,
        # ISSUE 10): the ``wire`` status block — derived from the program's
        # registered shapes, stamped once per run
        from draco_tpu.obs import numerics as numerics_mod

        self.heartbeat.set_wire(numerics_mod.wire_ledger(cfg,
                                                         self.setup.dim))
        # compile/retrace sentinel (obs/compile_watch.py): every XLA
        # executable build lands in compiles.jsonl + the trace's compile
        # lane, and a steady-state recompile of a labelled program trips
        # the guard (cfg.compile_guard) — the compile-once contract the
        # chunked regime's economics rest on
        self.compile_watch = make_compile_watch(cfg, self.tracer,
                                                self._is_main)
        self._shard_w = NamedSharding(self.mesh, P(WORKER_AXIS))
        # resilience wiring (draco_tpu/resilience): the parsed fault plan
        # (None without cfg.fault_spec), its one-shot host-event injector,
        # and the graceful-stop holder the active run() installs
        self._fault_plan = faults_mod.plan_from_cfg(cfg)
        self._injector = faults_mod.HostFaultInjector(self._fault_plan)
        self._stop: Optional[GracefulStop] = None
        self._stopped_step: Optional[int] = None
        # fault-plan overlays: over_budget pushes rows past the s budget,
        # adversary events mark declarative within-budget attack episodes
        # (time-varying adversaries, faults.apply_adversary)
        self._adv_schedule = faults_mod.apply_adversary(
            faults_mod.apply_over_budget(
                drng.adversary_schedule(cfg.seed, cfg.max_steps,
                                        cfg.num_workers,
                                        cfg.num_adversaries),
                self._fault_plan, cfg.worker_fail,
            ), self._fault_plan)
        # the fault plan's straggle events (sustained per-worker drops)
        # overlay the seeded straggler schedule — or materialize one when
        # the config ran with none (faults.apply_straggle)
        self._straggle_schedule = faults_mod.apply_straggle(
            drng.straggler_schedule(cfg.seed, cfg.max_steps, cfg.num_workers,
                                    cfg.straggle_count)
            if cfg.straggle_mode == "drop" and cfg.straggle_count > 0
            else None,
            self._fault_plan, cfg.num_workers, cfg.max_steps,
        )
        if getattr(cfg, "autopilot", "off") == "on" \
                and self._straggle_schedule is None:
            # the autopilot's quarantine actuates through the present-mask
            # schedule: materialize an all-present table up front so
            # exclusion is a host array write, never a program-signature
            # change (presents None→array would retrace the chunk program
            # under compile_guard="raise")
            self._straggle_schedule = np.zeros(
                (cfg.max_steps + 1, cfg.num_workers), dtype=bool)
        self._engine = None  # live ChunkedEngine while _run_chunked runs
        self._autopilot = None  # cached control/autopilot.Autopilot
        self._eager_step = None  # newest completed eager step (escalation)
        self._sched_steps = cfg.max_steps  # rows precomputed in the schedules
        self._group_seeds = drng.group_seeds(cfg.seed, max(cfg.num_groups, 1))
        # both prefetchers are lazy: the chunked path never touches the
        # per-step one (and vice versa), so neither thread pool should
        # exist until its loop actually runs (each may be wrapped in a
        # SupervisedPrefetcher — same get/depth/close surface)
        self._prefetch = None  # BatchPrefetcher | SupervisedPrefetcher
        self._chunk_prefetch = None  # ChunkPrefetcher | SupervisedPrefetcher
        self._start_step = 1
        if cfg.checkpoint_step:
            self.restore(cfg.checkpoint_step)

    # ---- data ------------------------------------------------------------
    def _batch_indices(self, step: int) -> np.ndarray:
        """Flat (n·B,) sample indices for 1-based training ``step``."""
        cfg = self.cfg
        n = len(self.ds)
        if cfg.approach == "baseline":
            return batching.indices_baseline(n, step - 1, cfg.num_workers,
                                             cfg.batch_size, cfg.seed)
        if cfg.approach == "maj_vote":
            return batching.indices_grouped(n, step - 1, cfg.num_workers,
                                            cfg.group_size, cfg.batch_size,
                                            self._group_seeds)
        return batching.indices_cyclic(n, step - 1, cfg.num_workers,
                                       cfg.batch_size, cfg.seed)

    def _supervised(self, factory):
        """Prefetcher restart supervision (resilience/supervisor.py):
        transient worker faults are retried with backoff up to
        cfg.prefetch_restarts times; 0 disables the wrapper entirely."""
        if self.cfg.prefetch_restarts <= 0:
            return factory()
        return SupervisedPrefetcher(factory,
                                    restarts=self.cfg.prefetch_restarts,
                                    tracer=self.tracer)

    def _host_batch(self, step: int):
        if self._prefetch is None:
            indices_fn = self._injector.wrap_step_fn(self._batch_indices)
            self._prefetch = self._supervised(lambda: BatchPrefetcher(
                self.ds, indices_fn, self.cfg.num_workers,
                self.cfg.batch_size, tracer=self.tracer
            ))
        return self._prefetch.get(step)

    def _device_batch(self, step: int):
        x, y = self._host_batch(step)
        return (
            put_global(np.asarray(x), self._shard_w),
            put_global(np.asarray(y), self._shard_w),
        )

    # ---- schedules -------------------------------------------------------
    def _ensure_schedules(self, n_steps: int) -> None:
        """Keep the adversary/straggler tables live past cfg.max_steps.

        ``run(max_steps=N)`` with N > cfg.max_steps used to replay the last
        precomputed row forever via ``min(step, cfg.max_steps)`` — block-wise
        callers like tools/time_to_acc.py silently trained against a frozen
        adversary set past the table end. Regeneration at the larger length
        is prefix-stable (each row consumes a fixed amount of the numpy
        stream), so already-trained steps keep their exact schedule."""
        if n_steps <= self._sched_steps:
            return
        cfg = self.cfg
        self._adv_schedule = faults_mod.apply_adversary(
            faults_mod.apply_over_budget(
                drng.adversary_schedule(cfg.seed, n_steps, cfg.num_workers,
                                        cfg.num_adversaries),
                self._fault_plan, cfg.worker_fail,
            ), self._fault_plan)
        if self._straggle_schedule is not None:
            self._straggle_schedule = faults_mod.apply_straggle(
                drng.straggler_schedule(
                    cfg.seed, n_steps, cfg.num_workers, cfg.straggle_count)
                if cfg.straggle_mode == "drop" and cfg.straggle_count > 0
                else None,
                self._fault_plan, cfg.num_workers, n_steps,
            )
            if self._straggle_schedule is None:
                # keep the autopilot's materialized all-present table live
                # at the new length
                self._straggle_schedule = np.zeros(
                    (n_steps + 1, cfg.num_workers), dtype=bool)
            if self._autopilot is not None:
                # a regenerated table must not silently re-admit workers
                # the policy still holds excluded (block-wise run() calls
                # past the precomputed length)
                self._autopilot.reapply_quarantines(self._straggle_schedule)
        self._sched_steps = n_steps

    # ---- chunking --------------------------------------------------------
    def _chunk_ranges(self, start: int, n_steps: int) -> list:
        """[(start, k), ...] covering steps [start, n_steps] — the shared
        boundary-snapping rule (batching.chunk_ranges, one implementation
        for this loop and the LM token loop)."""
        return batching.chunk_ranges(start, n_steps, self.cfg.steps_per_call,
                                     self.cfg.eval_freq)

    def _chunk_indices(self, start: int, k: int) -> np.ndarray:
        """(k, n·B) flat sample indices for 1-based steps [start, start+k) —
        row i bitwise equals _batch_indices(start + i)."""
        cfg = self.cfg
        n = len(self.ds)
        if cfg.approach == "baseline":
            return batching.indices_baseline_range(
                n, start - 1, k, cfg.num_workers, cfg.batch_size, cfg.seed)
        if cfg.approach == "maj_vote":
            return batching.indices_grouped_range(
                n, start - 1, k, cfg.num_workers, cfg.group_size,
                cfg.batch_size, self._group_seeds)
        return batching.indices_cyclic_range(
            n, start - 1, k, cfg.num_workers, cfg.batch_size, cfg.seed)

    def _device_chunk(self, rng: tuple, next_range: Optional[tuple]):
        """Assemble + upload one stacked chunk; submits next_range's host
        gather to the native pool before returning (double buffering)."""
        start, k = rng
        with self.tracer.span("gather", chunk_start=start, k=k):
            x, y = self._chunk_prefetch.get(rng, next_range)
        with self.tracer.span("upload", chunk_start=start, k=k):
            shard = NamedSharding(self.mesh, P(None, WORKER_AXIS))
            xs = put_global(np.asarray(x), shard)
            ys = put_global(np.asarray(y), shard)
            # numpy (uncommitted) so multi-host jit treats them as replicated
            masks = np.asarray(self._adv_schedule[start : start + k])
            presents = (
                np.asarray(~self._straggle_schedule[start : start + k])
                if self._straggle_schedule is not None
                else None
            )
        return xs, ys, masks, presents

    # ---- train -----------------------------------------------------------
    def run(self, max_steps: Optional[int] = None,
            profile_dir: Optional[str] = None,
            profile_steps: tuple = (3, 8)) -> dict:
        """Train. ``profile_dir`` captures a jax.profiler trace of steps
        [profile_steps) — the structured replacement for the reference's
        printed per-phase timers (SURVEY.md §5.1); the t_fetch/t_comp segment
        metrics keep the reference's names either way. With
        cfg.steps_per_call > 1 the scan-chunked loop runs instead of the
        eager per-step loop (module docstring); trace capture then snaps to
        the chunks containing profile_steps."""
        n_steps = max_steps if max_steps is not None else self.cfg.max_steps
        self._ensure_schedules(n_steps)
        # resilience envelope (ISSUE 6): SIGTERM/SIGINT become a
        # cooperative stop honored at step/chunk boundaries (boundary
        # checkpoint + "preempted" terminal state), and any unhandled
        # exception stamps a "crashed" terminal status.json with a one-line
        # cause before re-raising — operators and tools/trace_report.py can
        # distinguish crash / preempted / done without parsing stdout
        self._stopped_step = None
        try:
            with GracefulStop() as stop:
                self._stop = stop
                if self.cfg.steps_per_call > 1:
                    last = self._run_chunked(n_steps, profile_dir,
                                             profile_steps)
                else:
                    last = self._run_eager(n_steps, profile_dir,
                                           profile_steps)
        except ImmediateStopError as e:
            # second SIGTERM during a chunk (resilience/supervisor.py):
            # checkpoint the newest dispatched state NOW and end with the
            # terminal "preempted" status instead of finishing the grid
            self._stop = None
            return self._escalated_stop(e)
        except BaseException as e:
            self.heartbeat.terminal("crashed",
                                    cause=f"{type(e).__name__}: {e}")
            raise
        finally:
            self._stop = None
        if self._stopped_step is not None:
            self.heartbeat.terminal(
                "preempted",
                cause=f"graceful stop on {stop.signame}",
                resumable_step=(self._stopped_step
                                if self.cfg.train_dir else None),
            )
        else:
            self.heartbeat.terminal("done")
        # advance the cursor so a subsequent run(max_steps=...) continues
        # instead of retraining from step 1 (block-wise callers:
        # tools/time_to_acc.py); a preempted run's cursor stays at its
        # stop point (set by _snap_stop)
        if self._stopped_step is None:
            self._start_step = max(self._start_step, n_steps + 1)
        return last

    def _escalated_stop(self, e: ImmediateStopError) -> dict:
        """The second-signal escalation path: save a resumable checkpoint
        of the NEWEST dispatched state right now — blocking on the
        in-flight chunk if one is executing — and stamp the terminal
        ``preempted`` status. Un-flushed deferred metric records are lost
        (the operator asked for immediate teardown); the checkpoint and
        status.json are not."""
        eng = self._engine
        if eng is not None and eng.state is not None:
            self.state, step = eng.state, eng.last_end
        else:
            step = self._eager_step
        if self.cfg.train_dir and step is not None:
            with self.tracer.span("ckpt", at_step=step):
                ckpt.save(self.cfg.train_dir, step, self.state,
                          compress=self.cfg.compress_ckpt,
                          keep=self.cfg.keep_checkpoints)
        if step is not None:
            self._start_step = step + 1
        self.heartbeat.terminal(
            "preempted", cause=str(e),
            resumable_step=(step if self.cfg.train_dir and step is not None
                            else None))
        return {}

    def _check_stop(self, step: int) -> bool:
        """True when the run should stop after ``step``: a SIGTERM/SIGINT
        arrived (or the fault plan injects one here — delivered through
        the real handler path, supervisor.stop_requested)."""
        from draco_tpu.resilience.supervisor import stop_requested

        return stop_requested(self._stop, self._injector, step)

    def _snap_stop(self, step: int, already_saved: bool = False) -> None:
        """Honor a graceful stop at a step/chunk boundary: snap a resumable
        checkpoint there (the preemption/elasticity mechanism — resume with
        checkpoint_step=step or -1) and record where we stopped for the
        terminal heartbeat. ``already_saved``: the boundary path just
        checkpointed this exact step — don't pay the device_get + write
        twice."""
        if self.cfg.train_dir and not already_saved:
            with self.tracer.span("ckpt", at_step=step):
                ckpt.save(self.cfg.train_dir, step, self.state,
                          compress=self.cfg.compress_ckpt,
                          keep=self.cfg.keep_checkpoints)
        self._stopped_step = step
        if self._stop is not None:
            self._stop.stopped_step = step
        self._start_step = step + 1

    def _run_eager(self, n_steps: int, profile_dir, profile_steps) -> dict:
        cfg = self.cfg
        last = {}
        # the shared capture window (obs/profiling.py): start/stop/drain +
        # the merged-timeline anchor, one implementation for all four loop
        # sites (previously copy-pasted per site, ISSUE 9); on stop the
        # capture folds into the heartbeat's ``device`` status block
        win = profiler_window(profile_dir, profile_steps, self._is_main,
                              self.tracer,
                              on_stop=self.heartbeat.observe_device)
        for step in range(self._start_step, n_steps + 1):
            win.maybe_start(step)
            seg = Segments()
            seg.begin("fetch")
            with self.tracer.span("gather+upload", step=step):
                x, y = self._device_batch(step)
                # numpy (uncommitted) so multi-host jit treats it as
                # replicated
                mask = np.asarray(self._adv_schedule[step])
                present = (
                    np.asarray(~self._straggle_schedule[step])
                    if self._straggle_schedule is not None
                    else None
                )
            seg.end()

            seg.begin("comp")  # fwd+bwd+encode+gather+decode+update, one program
            with self.tracer.span("dispatch", step=step), \
                    self.compile_watch.expect("train_step"):
                if present is None:
                    self.state, metrics = self.setup.train_step(self.state, x,
                                                                y, mask)
                else:
                    self.state, metrics = self.setup.train_step(self.state, x,
                                                                y, mask,
                                                                present)
            with self.tracer.span("sync", step=step):
                # record_value: forensics bitmask columns materialize as
                # exact integer words, everything else as float
                metrics = {k: record_value(k, v) for k, v in metrics.items()}
                if present is not None:
                    metrics["present"] = float(present.sum())
                jax.block_until_ready(self.state.params)
            seg.end()

            win.maybe_stop(step, self.state.params)
            self._eager_step = step  # escalated-stop checkpoint cursor
            record = {"step": step, **metrics, **seg.as_dict()}
            last = record
            self.heartbeat.observe(record)
            if step % cfg.log_every == 0 or step == 1:
                self.writer.write(record)
            boundary = cfg.eval_freq and step % cfg.eval_freq == 0
            if boundary or step == n_steps:
                with self.tracer.span("flush", at_step=step):
                    self.writer.flush()
                    self.heartbeat.beat(step, n_steps,
                                        extra={**self._prefetch_depth(),
                                               **self.compile_watch
                                               .snapshot()})
                    self.tracer.flush()
            if boundary:
                self.evaluate(step)
                if cfg.train_dir:
                    with self.tracer.span("ckpt", at_step=step):
                        ckpt.save(cfg.train_dir, step, self.state,
                                  compress=cfg.compress_ckpt,
                                  keep=cfg.keep_checkpoints)
            if self._check_stop(step):
                with self.tracer.span("flush", at_step=step):
                    self.writer.flush()
                self._snap_stop(step, already_saved=bool(boundary))
                break
        win.stop(self.state.params)  # loop ended inside the window
        return last

    def _run_chunked(self, n_steps: int, profile_dir, profile_steps) -> dict:
        """The scan-fused loop, driven by the shared ``ChunkedEngine``
        (control/engine.py — one implementation with the LM token loop):
        dispatch train_many per chunk, upload the next chunk while the
        device runs the current one, defer metrics to flush boundaries."""
        cfg = self.cfg
        ranges = self._chunk_ranges(self._start_step, n_steps)
        if not ranges:
            return {}
        if self._chunk_prefetch is None:
            range_fn = self._injector.wrap_range_fn(self._chunk_indices)
            self._chunk_prefetch = self._supervised(lambda: ChunkPrefetcher(
                self.ds, range_fn, cfg.num_workers, cfg.batch_size,
                tracer=self.tracer
            ))
        from draco_tpu.control.clients import TrainerChunkClient
        from draco_tpu.control.engine import ChunkedEngine

        self._engine = ChunkedEngine(
            TrainerChunkClient(self), eval_freq=cfg.eval_freq,
            total_end=n_steps, tracer=self.tracer, heartbeat=self.heartbeat,
            compile_watch=self.compile_watch, writer=self.writer,
            autopilot=self._make_autopilot(), timed=True,
            profile_dir=profile_dir, profile_steps=profile_steps,
            is_main=self._is_main)
        self.state, last = self._engine.run(self.state, ranges)
        return last

    def _make_autopilot(self):
        """The adaptive coding autopilot (control/autopilot.py) when
        ``cfg.autopilot == "on"`` — None otherwise (the engine then runs
        the historical loop bit-for-bit). Built once and cached: regime
        and quarantine state outlive individual run() calls (block-wise
        callers), re-attached to each run's fresh client by the engine."""
        if getattr(self.cfg, "autopilot", "off") != "on":
            return None
        if self._autopilot is None:
            from draco_tpu.control.autopilot import make_autopilot

            self._autopilot = make_autopilot(self.cfg, self.heartbeat,
                                             dim=self.setup.dim)
        return self._autopilot

    def _prefetch_depth(self) -> dict:
        """Heartbeat extra: in-flight prefetch requests of whichever
        prefetcher the active regime runs, plus the supervision restart
        counter when wrapped (resilience/supervisor.py — the incident
        engine's starvation signal, ISSUE 13)."""
        p = self._chunk_prefetch if self._chunk_prefetch is not None \
            else self._prefetch
        if p is None:
            # no prefetcher, no depth claim: a constant 0 would read as
            # starvation to the incident engine (same rule as token_loop)
            return {}
        out = {"prefetch_depth": p.depth}
        if hasattr(p, "stats"):
            out.update(p.stats())
        return out

    # ---- eval ------------------------------------------------------------
    def evaluate(self, step: int, batch_size: Optional[int] = None) -> dict:
        """Full-split accuracy: the ragged final batch (n % bs != 0) is padded
        up to the compiled batch shape and masked out of the counts, so every
        test sample is scored exactly once (shared pad/mask loop:
        evaluator.masked_full_split_eval)."""
        from draco_tpu.training.evaluator import masked_full_split_eval

        with self.tracer.span("eval", at_step=step):
            p1, p5 = masked_full_split_eval(
                lambda x, y, valid: self.setup.eval_step(self.state, x, y,
                                                         valid),
                self.ds.test_x, self.ds.test_y,
                batch_size or self.cfg.test_batch_size,
            )
        rec = {"step": step, "prec1_test": p1, "prec5_test": p5}
        self.writer.write(rec)
        # eval cadence is rare and follows the loops' boundary flush, so
        # drain immediately — callers that never close() (perf tools) still
        # get a complete metrics.jsonl
        self.writer.flush()
        return rec

    def close(self):
        if self._prefetch is not None:
            self._prefetch.close()
        if self._chunk_prefetch is not None:
            self._chunk_prefetch.close()
        self.writer.close()
        self.compile_watch.stop()
        self.tracer.close()

    # ---- checkpoint ------------------------------------------------------
    def restore(self, step: int):
        """Resume from ``step`` (or the newest checkpoint when ``step ==
        -1``), walking back past corrupt checkpoints
        (resilience/supervisor.restore_with_walkback) — a torn newest
        checkpoint costs the steps since the previous good one, never the
        run."""
        # abstract tree must carry each leaf's sharding: on multi-host, save()
        # writes global jax.Arrays collectively, and a sharding-less restore
        # would fail (or come back host-local) exactly there
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
            self.state,
        )
        try:
            self.state, loaded, _skipped = restore_with_walkback(
                self.cfg.train_dir, step, abstract
            )
        except FileNotFoundError:
            if step != -1:
                raise
            # -1 is the restart-controller flag ("resume from whatever is
            # there"): an empty train_dir means a fresh start, not a crash
            # loop for jobs that died before their first checkpoint
            print(f"checkpoint_step=-1: no checkpoints in "
                  f"{self.cfg.train_dir!r}; starting fresh", flush=True)
            return
        self._start_step = loaded + 1
