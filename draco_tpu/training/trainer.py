"""Training loop — host-side orchestration around the jitted SPMD step.

Replaces the reference's per-role hot loops (SyncReplicasMaster_NN.start /
DistributedWorker.train and their coded variants, SURVEY.md §3) with one loop:
build batches (deterministic, approach-specific), device_put them sharded over
the worker axis, call the jitted step, emit metrics with the reference's
segment names, checkpoint every eval_freq steps.
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from draco_tpu import rng as drng
from draco_tpu.config import TrainConfig
from draco_tpu.data import batching
from draco_tpu.data.datasets import Dataset, load_dataset
from draco_tpu.data.prefetch import BatchPrefetcher
from draco_tpu.runtime import WORKER_AXIS, make_mesh, put_global
from draco_tpu.training.step import build_train_setup
from draco_tpu.utils import checkpoint as ckpt
from draco_tpu.utils.metrics import MetricWriter, Segments


class Trainer:
    def __init__(self, cfg: TrainConfig, mesh=None, dataset: Optional[Dataset] = None,
                 quiet: bool = False):
        self.cfg = cfg.validate()
        self.mesh = mesh if mesh is not None else make_mesh(cfg.num_workers)
        self.ds = dataset if dataset is not None else load_dataset(cfg.dataset, cfg.data_dir)
        self.setup = build_train_setup(cfg, self.mesh, dataset_name=self.ds.name)
        self.state = self.setup.state
        # on multi-host, only process 0 emits metrics (checkpoint saves stay
        # collective — every process contributes its addressable shards)
        self._is_main = jax.process_index() == 0
        self.writer = MetricWriter(cfg.train_dir if self._is_main else None,
                                   quiet=quiet or not self._is_main)
        self._shard_w = NamedSharding(self.mesh, P(WORKER_AXIS))
        self._adv_schedule = drng.adversary_schedule(
            cfg.seed, cfg.max_steps, cfg.num_workers, cfg.num_adversaries
        )
        self._straggle_schedule = (
            drng.straggler_schedule(cfg.seed, cfg.max_steps, cfg.num_workers,
                                    cfg.straggle_count)
            if cfg.straggle_mode == "drop" and cfg.straggle_count > 0
            else None
        )
        self._group_seeds = drng.group_seeds(cfg.seed, max(cfg.num_groups, 1))
        self._prefetch = BatchPrefetcher(
            self.ds, self._batch_indices, cfg.num_workers, cfg.batch_size
        )
        self._start_step = 1
        if cfg.checkpoint_step:
            self.restore(cfg.checkpoint_step)

    # ---- data ------------------------------------------------------------
    def _batch_indices(self, step: int) -> np.ndarray:
        """Flat (n·B,) sample indices for 1-based training ``step``."""
        cfg = self.cfg
        n = len(self.ds)
        if cfg.approach == "baseline":
            return batching.indices_baseline(n, step - 1, cfg.num_workers,
                                             cfg.batch_size, cfg.seed)
        if cfg.approach == "maj_vote":
            return batching.indices_grouped(n, step - 1, cfg.num_workers,
                                            cfg.group_size, cfg.batch_size,
                                            self._group_seeds)
        return batching.indices_cyclic(n, step - 1, cfg.num_workers,
                                       cfg.batch_size, cfg.seed)

    def _host_batch(self, step: int):
        return self._prefetch.get(step)

    def _device_batch(self, step: int):
        x, y = self._host_batch(step)
        return (
            put_global(np.asarray(x), self._shard_w),
            put_global(np.asarray(y), self._shard_w),
        )

    # ---- train -----------------------------------------------------------
    def run(self, max_steps: Optional[int] = None,
            profile_dir: Optional[str] = None,
            profile_steps: tuple = (3, 8)) -> dict:
        """Train. ``profile_dir`` captures a jax.profiler trace of steps
        [profile_steps) — the structured replacement for the reference's
        printed per-phase timers (SURVEY.md §5.1); the t_fetch/t_comp segment
        metrics keep the reference's names either way."""
        cfg = self.cfg
        last = {}
        n_steps = max_steps if max_steps is not None else cfg.max_steps
        for step in range(self._start_step, n_steps + 1):
            if profile_dir and step == profile_steps[0] and self._is_main:
                jax.profiler.start_trace(profile_dir)
            if profile_dir and step == profile_steps[1] and self._is_main:
                jax.profiler.stop_trace()
            seg = Segments()
            seg.begin("fetch")
            x, y = self._device_batch(step)
            # numpy (uncommitted) so multi-host jit treats it as replicated
            mask = np.asarray(self._adv_schedule[min(step, cfg.max_steps)])
            present = (
                np.asarray(~self._straggle_schedule[min(step, cfg.max_steps)])
                if self._straggle_schedule is not None
                else None
            )
            seg.end()

            seg.begin("comp")  # fwd+bwd+encode+gather+decode+update, one program
            if present is None:
                self.state, metrics = self.setup.train_step(self.state, x, y, mask)
            else:
                self.state, metrics = self.setup.train_step(self.state, x, y, mask,
                                                            present)
            metrics = {k: float(v) for k, v in metrics.items()}
            if present is not None:
                metrics["present"] = float(present.sum())
            jax.block_until_ready(self.state.params)
            seg.end()

            record = {"step": step, **metrics, **seg.as_dict()}
            last = record
            if step % cfg.log_every == 0 or step == 1:
                self.writer.write(record)
            if cfg.eval_freq and step % cfg.eval_freq == 0:
                self.evaluate(step)
                if cfg.train_dir:
                    ckpt.save(cfg.train_dir, step, self.state,
                              compress=cfg.compress_ckpt)
        # advance the cursor so a subsequent run(max_steps=...) continues
        # instead of retraining from step 1 (block-wise callers:
        # tools/time_to_acc.py)
        self._start_step = max(self._start_step, n_steps + 1)
        return last

    # ---- eval ------------------------------------------------------------
    def evaluate(self, step: int, batch_size: Optional[int] = None) -> dict:
        """Full-split accuracy: the ragged final batch (n % bs != 0) is padded
        up to the compiled batch shape and masked out of the counts, so every
        test sample is scored exactly once (shared pad/mask loop:
        evaluator.masked_full_split_eval)."""
        from draco_tpu.training.evaluator import masked_full_split_eval

        p1, p5 = masked_full_split_eval(
            lambda x, y, valid: self.setup.eval_step(self.state, x, y, valid),
            self.ds.test_x, self.ds.test_y,
            batch_size or self.cfg.test_batch_size,
        )
        rec = {"step": step, "prec1_test": p1, "prec5_test": p5}
        self.writer.write(rec)
        return rec

    def close(self):
        self._prefetch.close()
        self.writer.close()

    # ---- checkpoint ------------------------------------------------------
    def restore(self, step: int):
        # abstract tree must carry each leaf's sharding: on multi-host, save()
        # writes global jax.Arrays collectively, and a sharding-less restore
        # would fail (or come back host-local) exactly there
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
            self.state,
        )
        self.state = ckpt.load(self.cfg.train_dir, step, abstract)
        self._start_step = step + 1
