"""The SPMD training step — the reference's whole PS↔worker protocol as one
jitted program.

One call to the returned ``train_step`` does what the reference spreads over
rank-0 and rank-1..P processes and an MPI tag protocol (SURVEY.md §3.1-3.3):

  reference                                   here
  ---------                                   ----
  async_bcast_step / weights Bcast            params replicated on the mesh —
    (baseline_master.py:156-186)              nothing moves
  worker forward/backward + layer streaming   vmap'ed jax.grad over the
    (baseline_worker.py:225, resnet_split)    worker-sharded batch axis
  err_simulation at every send site           branch-free masked injection
    (model_ops/utils.py:6)                    (draco_tpu.attacks)
  P×L Irecv + Waitany drain                   XLA all-gather of the (n, d)
    (baseline_master.py:90-116)               gradient matrix over ICI
  decode / vote / median / krum on rank 0     the same math, replicated on
    (rep/cyclic/baseline_master)              every device after the gather
  SGDModified.step(grads)                     optimizer update on replicated
    (sgd_modified.py:53)                      params

The worker axis ``w`` is a real array axis: per-worker gradients live in an
(n, d) matrix sharded over the mesh; aggregation contracts over that axis and
XLA inserts the collectives. No tags, no buffers, no races by construction
(SURVEY.md §5.2).
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from draco_tpu import aggregation, attacks, optim, rng as drng
from draco_tpu.config import TrainConfig
from draco_tpu.coding import cyclic as cyclic_mod
from draco_tpu.coding import repetition as rep_mod
from draco_tpu.data import augment as augment_mod
from draco_tpu.models import build_model, input_shape
from draco_tpu.obs import forensics as forensics_mod
from draco_tpu.obs import numerics as numerics_mod
from draco_tpu.resilience import faults as faults_mod
from draco_tpu.runtime import WORKER_AXIS


def _maybe_guard(cfg, prev_state, new_state, agg, health, present, out):
    """Fold the in-graph step guard (resilience/guards.py) into a CNN step
    body's tail: untrusted updates become branch-free carry passthrough and
    the guard columns land in the metrics dict. Identity when
    cfg.step_guard is off — the unguarded program is unchanged."""
    if cfg.step_guard != "on":
        return new_state
    from draco_tpu.resilience import guards

    new_state, cols = guards.guard_update(cfg, prev_state, new_state, agg,
                                          health, present)
    out.update(cols)
    return new_state


def _metrics(losses, precs, present=None):
    """Per-worker (n,) metrics -> scalars, ignoring absent workers."""
    if present is None:
        return {"loss": jnp.mean(losses), "prec1": jnp.mean(precs)}
    w = present.astype(losses.dtype)
    denom = jnp.maximum(jnp.sum(w), 1.0)
    return {"loss": jnp.sum(losses * w) / denom,
            "prec1": jnp.sum(precs * w) / denom}


def _detection_metrics(flagged, adv_mask, present):
    """Per-step detection counts vs the seeded schedules (both of which are
    step INPUTS, so the comparison runs in-graph — no host traffic): tp =
    flagged ∧ adversarial ∧ present, adv = adversarial ∧ present. Flush
    boundaries fold these into precision/recall (obs/heartbeat.py). A
    straggling adversary's row never arrives — neither detectable nor
    ground truth, hence the ``present`` gate on both sides."""
    pres = (jnp.ones_like(adv_mask, dtype=bool) if present is None
            else present)
    adv_live = adv_mask & pres
    flagged = flagged & pres
    return {
        "det_flagged": jnp.sum(flagged.astype(jnp.int32)),
        "det_tp": jnp.sum((flagged & adv_live).astype(jnp.int32)),
        "det_adv": jnp.sum(adv_live.astype(jnp.int32)),
    }


class TrainState(NamedTuple):
    params: Any  # replicated pytree
    opt_state: Any  # replicated
    batch_stats: Any  # per-worker (leading n axis) or None
    step: jnp.ndarray  # scalar int32


class TrainSetup(NamedTuple):
    """Everything the trainer loop needs, built once from a TrainConfig."""

    model: Any
    state: TrainState
    train_step: Any  # (state, x, y, adv_mask) -> (state, metrics)
    # (state, x, y, valid) -> (correct@1 count, correct@5 count)
    eval_step: Any
    code: Any  # CyclicCode | RepetitionCode | None
    unravel: Any  # flat (d,) -> params pytree
    dim: int
    # K fused steps in ONE device program:
    # (state, xs (K,n,B,...), ys (K,n,B), masks (K,n), presents (K,n)|None)
    #   -> (state, metrics (K, len(metric_names)) float32)
    train_many: Any = None
    metric_names: tuple = ()  # column order of train_many's metrics block


def _cross_entropy(logits, labels):
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))


def _flatten_tree(tree) -> jnp.ndarray:
    return jnp.concatenate(
        [jnp.reshape(x, (-1,)) for x in jax.tree.leaves(tree)])


def _make_unravel(params):
    """Returns (unravel, dim, offsets) — offsets are the per-leaf segment
    boundaries in the flat vector, the "layers" of layer-granularity decode
    (the reference decodes each parameter tensor separately,
    cyclic_master.py:125-129)."""
    leaves, treedef = jax.tree.flatten(params)
    shapes = [l.shape for l in leaves]
    sizes = [int(np.prod(s)) for s in shapes]
    offsets = np.cumsum([0] + sizes)

    def unravel(flat):
        parts = [
            jnp.reshape(flat[offsets[i] : offsets[i + 1]], shapes[i])
            for i in range(len(shapes))
        ]
        return jax.tree.unflatten(treedef, parts)

    return unravel, int(offsets[-1]), offsets


def build_train_setup(cfg: TrainConfig, mesh,
                      dataset_name: Optional[str] = None) -> TrainSetup:
    """Construct model/state and the jitted train & eval steps for
    cfg.approach."""
    cfg.validate()
    n = cfg.num_workers
    shape = input_shape(dataset_name or cfg.dataset)
    model = build_model(cfg.network, dtype=cfg.compute_dtype)
    use_aug = "cifar" in (dataset_name or cfg.dataset).lower()

    root = jax.random.key(cfg.seed)
    init_x = jnp.zeros((2,) + shape, jnp.float32)
    variables = model.init(
        {"params": root, "dropout": jax.random.fold_in(root, 1)},
                           init_x, train=True)
    params = variables["params"]
    has_bn = "batch_stats" in variables
    # per-worker BN statistics (never aggregated — reference
    # worker/utils.py:46-48)
    batch_stats = (
        jax.tree.map(lambda x: jnp.broadcast_to(x, (n,) + x.shape),
                     variables["batch_stats"])
        if has_bn
        else None
    )

    opt = optim.build_optimizer_from_cfg(cfg)
    opt_state = opt.init(params)
    unravel, dim, leaf_offsets = _make_unravel(params)

    # lazy: parallel/__init__ imports this module
    from draco_tpu.parallel.partition import (
        REPLICATED, WORKER_ROWS, WORKER_ROWS3, sharding,
    )

    repl = sharding(mesh, REPLICATED)
    shard_w = sharding(mesh, WORKER_ROWS)

    state = TrainState(
        params=jax.device_put(params, repl),
        opt_state=jax.device_put(opt_state, repl),
        batch_stats=jax.device_put(batch_stats, shard_w) if has_bn else None,
        step=jax.device_put(jnp.asarray(1, jnp.int32), repl),  # STEP_START_=1
    )

    # ---- per-(lane) loss/grad --------------------------------------------
    def loss_fn(p, stats, x, y, dkey):
        vs = {"params": p}
        if has_bn:
            vs["batch_stats"] = stats
        out = model.apply(
            vs, x, train=True,
            mutable=["batch_stats"] if has_bn else False,
            rngs={"dropout": dkey},
        )
        if has_bn:
            logits, mutated = out
            new_stats = mutated["batch_stats"]
        else:
            logits = out
            new_stats = stats
        loss = _cross_entropy(logits, y)
        prec1 = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
        return loss, (new_stats, prec1)

    # optional rematerialisation: recompute activations in the backward pass
    # instead of keeping them in HBM (jax.checkpoint) — lets larger per-worker
    # batches / deeper models fit, trading ~1/3 more FLOPs for memory
    lane_loss = jax.checkpoint(loss_fn) if cfg.remat else loss_fn

    def lane(p, stats, x, y, dkey):
        """One logical worker/batch lane ->
        (flat grad, new_stats, loss, prec1)."""
        # named scope: fwd/bwd ops group under Draco's "comp" phase in XProf
        # device traces (reference segment names, cyclic_worker.py:154-156)
        with jax.named_scope("draco_comp"):
            (loss, (new_stats, prec1)), g = jax.value_and_grad(
                lane_loss, has_aux=True
            )(p, stats, x, y, dkey)
        return _flatten_tree(g), new_stats, loss, prec1

    def apply_update(state: TrainState, flat_grad, new_stats):
        with jax.named_scope("draco_update"):
            grads_tree = unravel(flat_grad)
            updates, new_opt = opt.update(grads_tree, state.opt_state,
                                          state.params)
            new_params = jax.tree.map(lambda p, u: p + u, state.params,
                                      updates)
        return TrainState(
            params=new_params,
            opt_state=new_opt,
            batch_stats=new_stats,
            step=state.step + 1,
        )

    adv_mag = cfg.adversarial

    def prep_rows(state, x, y):
        """Augment + dropout keys per *global batch row* k — any worker
        computing batch k sees identical data and rng. The per-batch-row
        discipline both algebraic code families (cyclic, approx) share:
        it is what makes the shared-redundancy encode exact."""
        if use_aug:
            keys = jax.vmap(
                lambda k: drng.fold(jax.random.key(cfg.seed + 2),
                                    state.step, k)
            )(jnp.arange(n))
            x = jax.vmap(augment_mod.augment_batch)(x, keys)
        dkeys = jax.vmap(
            lambda k: drng.fold(jax.random.key(cfg.seed + 3), state.step, k)
        )(jnp.arange(n))
        return x, y, dkeys

    # ---- approach-specific step bodies -----------------------------------
    if cfg.approach == "baseline":
        code = None
        rep_code = None

        def step_body(state: TrainState, x, y, adv_mask, present=None):
            # x, y: (n, B, ...) sharded over w; aug key per (step, worker)
            if use_aug:
                keys = jax.vmap(
                    lambda i: drng.fold(jax.random.key(cfg.seed + 2),
                                        state.step, i)
                )(jnp.arange(n))
                x = jax.vmap(augment_mod.augment_batch)(x, keys)
            dkeys = jax.vmap(
                lambda i: drng.fold(jax.random.key(cfg.seed + 3),
                                    state.step, i)
            )(jnp.arange(n))
            grads, new_stats, losses, precs = jax.vmap(
                lane, in_axes=(None, 0, 0, 0, 0))(
                state.params, state.batch_stats, x, y, dkeys
            )
            grads = jax.lax.with_sharding_constraint(grads, shard_w)
            grads = faults_mod.corrupt_grads(grads, cfg, state.step)
            grads = attacks.inject_plain(grads, adv_mask, cfg.err_mode,
                                         adv_mag,
                                         n_mal=cfg.num_adversaries,
                                         step=state.step, seed=cfg.seed)
            with jax.named_scope("draco_decode"):
                agg = aggregation.aggregate(grads, cfg.mode,
                                            s=cfg.worker_fail,
                                            geomedian_iters=(
                                                cfg.geomedian_iters),
                                            present=present)
            new_state = apply_update(state, agg, new_stats)
            out = _metrics(losses, precs, present)
            # no exactness certificate on approximate rules: the guard's
            # only signal here is the global-finite check
            new_state = _maybe_guard(cfg, state, new_state, agg, None,
                                     present, out)
            return new_state, out

    elif cfg.approach == "maj_vote":
        code = None
        rep_code = rep_mod.build_repetition_code(n, cfg.group_size)
        group_ids = jnp.asarray(np.arange(n) // cfg.group_size, jnp.int32)

        def step_body(state: TrainState, x, y, adv_mask, present=None):
            # group members carry identical batches (batching layer guarantees
            # it); aug + dropout keys fold the *group* id so lanes stay
            # bitwise identical within a group — the vote's soundness condition
            if use_aug:
                keys = jax.vmap(
                    lambda gid: drng.fold(jax.random.key(cfg.seed + 2),
                                          state.step, gid)
                )(group_ids)
                x = jax.vmap(augment_mod.augment_batch)(x, keys)
            dkeys = jax.vmap(
                lambda gid: drng.fold(jax.random.key(cfg.seed + 3),
                                      state.step, gid)
            )(group_ids)
            grads, new_stats, losses, precs = jax.vmap(
                lane, in_axes=(None, 0, 0, 0, 0))(
                state.params, state.batch_stats, x, y, dkeys
            )
            grads = jax.lax.with_sharding_constraint(grads, shard_w)
            grads = faults_mod.corrupt_grads(grads, cfg, state.step)
            grads = attacks.inject_plain(grads, adv_mask, cfg.err_mode,
                                         adv_mag,
                                         n_mal=cfg.num_adversaries,
                                         step=state.step, seed=cfg.seed)
            # per-step fingerprint salt, identical on every device (folded
            # from replicated state.step). Being seed-derived it is NOT
            # secret from a participant that knows the experiment seed —
            # cfg.vote_check="exact" is the collision-free option for that
            # threat model (repetition.py module docstring, tier 3).
            vkey = drng.fold(jax.random.key(cfg.seed + 4), state.step)
            # the REAL narrow wire (ISSUE 15): this family's wire IS the
            # raw gradient rows — quantize them into narrow buffers (the
            # shared noise draw keeps within-group rows bitwise identical,
            # the vote's soundness condition; pinned in tests/test_wire.py)
            # and vote over the widened rows. Identity on the f32 wire.
            vote_rows = grads
            if cfg.wire_dtype != "f32":
                vote_rows, _wire = numerics_mod.narrow_wire_single(
                    cfg, grads, step=state.step,
                    constrain=lambda r: jax.lax.with_sharding_constraint(
                        r, shard_w))
            with jax.named_scope("draco_decode"):
                voted, vhealth = rep_mod.majority_vote(
                    rep_code, vote_rows, present=present, key=vkey,
                    method=cfg.vote_check, with_health=True)
            new_state = apply_update(state, voted, new_stats)
            out = _metrics(losses, precs, present)
            # vote health (telemetry columns; coding/repetition.py):
            # agreement fraction + flagged groups, and the per-row flag set
            # scored against the seeded schedules — all in-graph
            out["vote_agree"] = vhealth["vote_agree"]
            out["flagged_groups"] = vhealth["flagged_groups"]
            out.update(_detection_metrics(vhealth["flagged"], adv_mask,
                                          present))
            # numerics observatory (obs/numerics.py, ISSUE 10): this
            # family's wire IS the raw gradient rows; the shadow re-votes
            # over the quantized rows (deterministic rounding preserves
            # within-group bitwise equality, the vote's soundness condition)
            if numerics_mod.watch_enabled(cfg):
                if cfg.numerics_watch == "on":
                    out.update(numerics_mod.numerics_columns(
                        cfg, [grads], [vote_rows], voted))
                if cfg.shadow_wire != "off":
                    out.update(numerics_mod.majvote_shadow(
                        cfg, rep_code, grads, voted, vhealth, vkey,
                        present, adv_mask, state.step))
            # per-worker forensics columns (obs/forensics): the vote's own
            # out-voted set ∪ non-finite ingest rows, packed with the
            # present + seeded-adversary masks to ride the metric block
            out.update(forensics_mod.pack_mask_columns(
                vhealth["flagged"] | forensics_mod.nonfinite_rows(grads),
                present, adv_mask))
            # guard signals: finite vote + out-voted rows (vote
            # disagreement) within the s budget
            new_state = _maybe_guard(cfg, state, new_state, voted,
                                     {"flagged": vhealth["flagged"]},
                                     present, out)
            return new_state, out

    elif cfg.approach == "approx":
        # approximate gradient code (coding/approx.py; ISSUE 8): per-batch
        # rows computed once (shared redundancy — validate() pins it),
        # replication-weighted partial sums, optimal-decoding partial
        # recovery. No adversary injection: validate() rejects live
        # adversaries (no Byzantine certificate) — the straggler `present`
        # mask is this family's whole fault surface.
        from draco_tpu.parallel.common import (approx_aggregate,
                                               build_code_from_cfg)

        code = build_code_from_cfg(cfg)
        rep_code = None

        def step_body(state: TrainState, x, y, adv_mask, present=None):
            x, y, dkeys = prep_rows(state, x, y)
            grads, new_stats, losses, precs = jax.vmap(
                lane, in_axes=(None, 0, 0, 0, 0)
            )(state.params, state.batch_stats, x, y, dkeys)
            grads = jax.lax.with_sharding_constraint(grads, shard_w)
            grads = faults_mod.corrupt_grads(grads, cfg, state.step)
            # the ONE shared encode→mask→decode→forensics sequence
            # (parallel/common.approx_aggregate — identical semantics with
            # the LM routes by construction)
            decoded, health = approx_aggregate(
                code, grads, present=present,
                constrain=lambda r: jax.lax.with_sharding_constraint(
                    r, shard_w),
                cfg=cfg, adv_mask=adv_mask, step=state.step)
            new_state = apply_update(state, decoded, new_stats)
            out = _metrics(losses, precs, present)
            # residual-vs-bound health + packed forensics masks (accused =
            # non-finite ingest rows only — a scheduled straggler is never
            # accused); one schema with the LM routes
            from draco_tpu.parallel.common import decode_health_metrics

            out.update(decode_health_metrics(health, adv_mask, present))
            # guard signals: finite decode + residual within its analytic
            # bound (guards.assess's approx branch)
            new_state = _maybe_guard(cfg, state, new_state, decoded, health,
                                     present, out)
            return new_state, out

    elif cfg.approach == "cyclic":
        # one shared constructor with the LM routes: CyclicCode flat, or —
        # under topology="tree" (ISSUE 17) — a TreeCode wrapping the ONE
        # small group code at the (fanout, s_g) shape
        from draco_tpu.parallel.common import build_code_from_cfg

        code = build_code_from_cfg(cfg)
        tree = getattr(cfg, "topology", "flat") == "tree"
        if tree:
            from draco_tpu.coding import topology as topology_mod
        rep_code = None
        if not tree:
            batch_ids = jnp.asarray(code.batch_ids)  # (n, hat_s)
            hat_s = code.hat_s
        # decode lowering (ISSUE 12): resolved ONCE per setup — dispatch
        # depends only on cfg + the attached backend, so the jitted step
        # bodies close over a static tag (no retraces)
        from draco_tpu.ops.decode_kernels import resolve_decode_impl

        decode_impl = resolve_decode_impl(cfg.decode_impl)

        if cfg.redundancy == "shared":

            def compute_encoded(state, x, y):
                # each batch row computed once; rows then combined with the
                # masked W — identical semantics, r× less compute (TPU-native
                # fast path; see config.redundancy)
                x, y, dkeys = prep_rows(state, x, y)
                grads, new_stats, losses, precs = jax.vmap(
                    lane, in_axes=(None, 0, 0, 0, 0)
                )(state.params, state.batch_stats, x, y, dkeys)
                grads = jax.lax.with_sharding_constraint(grads, shard_w)
                grads = faults_mod.corrupt_grads(grads, cfg, state.step)
                # ingest-row forensics: attribute non-finite rows BEFORE the
                # algebraic encode smears them (forensics.nonfinite_rows)
                bad_rows = forensics_mod.nonfinite_rows(grads)
                # grad-stage numerics (obs/numerics.py): computed here,
                # where the pre-encode rows still exist
                grad_watch = (numerics_mod.stage_columns(
                    "grad", [grads], cfg.shadow_block)
                    if cfg.numerics_watch == "on" else {})
                with jax.named_scope("draco_encode"):
                    if tree:
                        # each leaf group encodes with the shared small
                        # code; rows stay worker-indexed (n, d)
                        enc_re, enc_im = topology_mod.encode_tree(code,
                                                                  grads)
                    else:
                        enc_re, enc_im = cyclic_mod.encode_shared(code,
                                                                  grads)
                return (enc_re, enc_im, new_stats, losses, precs, bad_rows,
                        grad_watch)

        else:  # "simulate": the reference's true r× redundant compute

            def compute_encoded(state, x, y):
                x, y, dkeys = prep_rows(state, x, y)
                # worker i gathers its hat_s batch rows: (n, hat_s, B, ...)
                xw = x[batch_ids]
                yw = y[batch_ids]
                kw = dkeys[batch_ids]
                # worker's BN stats replicated over its hat_s lanes
                stats_w = (
                    jax.tree.map(
                        lambda t: jnp.broadcast_to(
                            t[:, None], (n, hat_s) + t.shape[1:]),
                        state.batch_stats,
                    )
                    if has_bn
                    else None
                )
                def worker_lane(stats_i, x_i, y_i, k_i):
                    return jax.vmap(lane, in_axes=(None, 0, 0, 0, 0))(
                        state.params, stats_i, x_i, y_i, k_i
                    )
                grads, new_stats, losses, precs = jax.vmap(worker_lane)(
                    stats_w, xw, yw, kw
                )  # grads: (n, hat_s, d)
                grads = jax.lax.with_sharding_constraint(
                    grads, sharding(mesh, WORKER_ROWS3)
                )
                grads = faults_mod.corrupt_grads(grads, cfg, state.step)
                # ingest-row forensics: any non-finite value in worker i's
                # hat_s redundant lanes attributes to worker i
                bad_rows = forensics_mod.nonfinite_rows(grads)
                grad_watch = (numerics_mod.stage_columns(
                    "grad", [grads], cfg.shadow_block)
                    if cfg.numerics_watch == "on" else {})
                with jax.named_scope("draco_encode"):
                    enc_re, enc_im = cyclic_mod.encode(code, grads)
                # fold the per-sub-batch stats back to one per worker
                new_stats = (
                    jax.tree.map(lambda t: jnp.mean(t, axis=1), new_stats)
                    if has_bn
                    else None
                )
                return (enc_re, enc_im, new_stats, jnp.mean(losses, 1),
                        jnp.mean(precs, 1), bad_rows, grad_watch)

        def step_body(state: TrainState, x, y, adv_mask, present=None):
            (enc_re, enc_im, new_stats, losses, precs, bad_rows,
             grad_watch) = compute_encoded(state, x, y)
            with jax.named_scope("draco_encode"):
                enc_re, enc_im = attacks.inject_cyclic(
                    enc_re, enc_im, adv_mask,
                                                       cfg.err_mode, adv_mag,
                                                       step=state.step,
                                                       seed=cfg.seed)
                if present is not None:
                    # straggler rows never arrive: zero-fill (erasures at known
                    # positions; decode recovers exactly within the budget —
                    # config.validate)
                    pw = present[:, None].astype(enc_re.dtype)
                    enc_re = enc_re * pw
                    enc_im = enc_im * pw
                # the REAL narrow wire (ISSUE 15): the codeword pair is
                # rounded into narrow bf16/int8 buffers — THE arrays that
                # cross the worker-sharding boundary (the constraint pins
                # them, not a widened copy) — and widened to f32 only for
                # the decode. Identity (no added ops) on the f32 wire.
                if cfg.wire_dtype != "f32":
                    enc_re, enc_im, wire = numerics_mod.narrow_wire_pair(
                        cfg, enc_re, enc_im, step=state.step,
                        constrain=lambda r: jax.lax.with_sharding_constraint(
                            r, shard_w))
                else:
                    wire = None
                    enc_re = jax.lax.with_sharding_constraint(enc_re, shard_w)
                    enc_im = jax.lax.with_sharding_constraint(enc_im, shard_w)
            # in-graph decode projection — no d-length program constant
            # (rng.random_projection_factors_in_graph docstring)
            rand_factor = drng.random_projection_factors_in_graph(cfg.seed,
                                                                  dim)
            # quantization-aware flag threshold + locator λ for the narrow
            # wire (obs/numerics.wire_decode_params; f32 keeps the exact
            # HEALTH_REL_TOL / λ=0 path bitwise)
            if tree:
                # per-group decode runs at the GROUP shape: thresholds
                # come from the (fanout, s_g) table row, not the flat one
                wire_tol, wire_lam = numerics_mod.wire_decode_params(
                    cfg, n=code.plan.fanout, s=code.group_code.s)
            else:
                wire_tol, wire_lam = numerics_mod.wire_decode_params(cfg)
            rel_tol = (cyclic_mod.HEALTH_REL_TOL if wire_tol is None
                       else wire_tol)
            segments = int(getattr(cfg, "wire_segments", 1))
            with jax.named_scope("draco_decode"):
                if tree:
                    # hierarchical decode (ISSUE 17): per-group small-n
                    # decode (segmented under the streaming wire), level-
                    # structured combine, PR 16-style fold — honest comes
                    # back already folded to (n,)
                    bounds = (numerics_mod.cfg_segment_bounds(cfg, dim)
                              if segments > 1 else None)
                    decoded, honest, health = (
                        topology_mod.decode_tree_cyclic(
                            code, enc_re, enc_im, rand_factor,
                            present=present, rel_tol=rel_tol,
                            impl=decode_impl, lam=wire_lam, wire=wire,
                            bounds=bounds))
                elif cfg.decode_granularity == "layer":
                    if segments > 1:
                        # streaming segmented wire (ISSUE 16): the decode
                        # partition refines the leaf boundaries by the
                        # quantum-aligned segment cuts; honest/health fold
                        # across the finer partition exactly as per-layer
                        from draco_tpu.parallel.common import (
                            segment_decode_bounds)

                        bounds = segment_decode_bounds(cfg, dim,
                                                       leaf_offsets)
                        decoded, honest_l, health = (
                            cyclic_mod.decode_segments(
                                code, enc_re, enc_im, rand_factor, bounds,
                                present=present, with_health=True,
                                impl=decode_impl, rel_tol=rel_tol,
                                lam=wire_lam, wire=wire))
                    else:
                        # per-parameter-tensor locator + projection, like
                        # the reference's per-layer decode loop
                        # (cyclic_master.py:125-129)
                        decoded, honest_l, health = cyclic_mod.decode_layers(
                            code, enc_re, enc_im, rand_factor, leaf_offsets,
                            present=present, with_health=True,
                            impl=decode_impl, rel_tol=rel_tol, lam=wire_lam,
                        )
                    honest = jnp.all(honest_l, axis=0)
                elif segments > 1:
                    # streaming segmented wire (ISSUE 16): per-segment
                    # syndromes + locators, folded to one per-step verdict
                    # (coding/cyclic.decode_segments docstring)
                    bounds = numerics_mod.cfg_segment_bounds(cfg, dim)
                    decoded, honest_l, health = cyclic_mod.decode_segments(
                        code, enc_re, enc_im, rand_factor, bounds,
                        present=present, with_health=True, impl=decode_impl,
                        rel_tol=rel_tol, lam=wire_lam, wire=wire)
                    honest = jnp.all(honest_l, axis=0)
                else:
                    decoded, honest, health = cyclic_mod.decode(
                        code, enc_re, enc_im, rand_factor, present=present,
                        with_health=True, impl=decode_impl,
                        rel_tol=rel_tol, lam=wire_lam, wire=wire)
            new_state = apply_update(state, decoded, new_stats)
            out = _metrics(losses, precs, present)
            out["honest_located"] = jnp.sum(honest.astype(jnp.int32))
            # decode health (telemetry columns; coding/cyclic._locate_v
            # docstring): residual ≈ 0 is the paper's exactness guarantee
            # made observable, the flag set scores against the seeded
            # schedules — all in-graph, no host traffic. One schema with
            # the LM routes (common.decode_health_metrics; imported lazily,
            # parallel/__init__ imports this module). The packed forensics
            # masks ride along (accused = flagged ∪ loud ∪ bad_rows)
            from draco_tpu.parallel.common import decode_health_metrics

            health["bad_rows"] = bad_rows
            # numerics observatory (obs/numerics.py, ISSUE 10): wire/agg
            # stages + the shadow-quantized decode join the grad-stage
            # columns from compute_encoded; decode_health_metrics merges
            # the stash — the f32 decode above alone feeds the update
            if numerics_mod.watch_enabled(cfg):
                watch = dict(grad_watch)
                if cfg.numerics_watch == "on":
                    watch.update(numerics_mod.stage_columns(
                        "wire", [enc_re, enc_im], cfg.shadow_block))
                    watch.update(numerics_mod.stage_columns(
                        "agg", [decoded], cfg.shadow_block))
                if cfg.shadow_wire != "off":
                    watch.update(numerics_mod.cyclic_shadow(
                        cfg, code, enc_re, enc_im, decoded, health,
                        rand_factor, leaf_offsets, present, adv_mask,
                        state.step))
                health["watch"] = watch
            out.update(decode_health_metrics(health, adv_mask, present))
            # guard signals: finite decode + loud residual + located rows
            # beyond the locator budget (the beyond-budget fault class)
            new_state = _maybe_guard(cfg, state, new_state, decoded, health,
                                     present, out)
            return new_state, out

    else:  # pragma: no cover
        raise ValueError(cfg.approach)

    # ---- eval ------------------------------------------------------------
    def eval_body(state: TrainState, x, y, valid):
        """Returns correct-prediction COUNTS over the ``valid`` mask (not
        means): the trainer pads the final ragged batch up to the compiled
        shape and divides the summed counts by the true test-set size, so no
        tail sample is dropped and every batch weighs by its real length
        (reference evaluates the full split,
        distributed_evaluator.py:92-110)."""
        vs = {"params": state.params}
        if has_bn:
            # evaluate with worker-0's running stats (reference evaluates a
            # single worker's checkpointed state, distributed_evaluator.py:119)
            vs["batch_stats"] = jax.tree.map(lambda t: t[0], state.batch_stats)
        logits = model.apply(vs, x, train=False)
        ok1 = (jnp.argmax(logits, -1) == y) & valid
        ok5 = jnp.any(jax.lax.top_k(logits, 5)[1] == y[:, None],
                      axis=1) & valid
        return (jnp.sum(ok1.astype(jnp.float32)),
                jnp.sum(ok5.astype(jnp.float32)))

    # ---- K fused steps in one device program ------------------------------
    # The reference pays its PS round trip once per step; the timing harness
    # (bench.py / utils/timing.py) already had to fold iterations into one
    # lax.scan to measure honestly behind remote-dispatch backends (~70 ms
    # RTT per launch, PERF.md §0). train_many makes that fold the PRODUCTION
    # loop: K full coded steps — fwd/bwd, encode, gather, decode, update —
    # scan-chained with the state carry donated, schedules sliced on device,
    # and per-step metrics accumulated into one (K, m) block the host
    # fetches once per chunk. The chunk length K is the operands' leading
    # dim, so one program per distinct chunk size (the trainer's main K and
    # its remainder chunks), not per call.
    # decode-health / forensics / numerics / guard telemetry columns ride
    # the same block (ISSUES 4/7/10): the per-step values are in-graph
    # scalars, so the chunked regime ships them for free in the one
    # existing per-flush fetch. The optional families come from the ONE
    # shared assembly (parallel/common.metric_family_names) so this path
    # and every LM route declare each family exactly once; only the
    # CNN-specific base columns (prec1, cyclic honest_located) live here.
    from draco_tpu.parallel.common import metric_family_names

    metric_names = ("loss", "prec1")
    if cfg.approach == "cyclic":
        metric_names += ("honest_located",)
    metric_names += metric_family_names(cfg)

    def many_body(state: TrainState, xs, ys, masks, presents):
        def body(st, operand):
            x, y, adv_mask, present = operand
            st, metrics = step_body(st, x, y, adv_mask, present)
            row = jnp.stack(
                [jnp.asarray(metrics[k], jnp.float32) for k in metric_names]
            )
            return st, row

        # presents=None threads through as an empty pytree: the scan slices
        # per-step (n,) rows from each (K, n) schedule on device
        return jax.lax.scan(body, state, (xs, ys, masks, presents))

    with mesh:
        train_step = jax.jit(step_body, donate_argnums=(0,))
        train_many = jax.jit(many_body, donate_argnums=(0,))
        eval_step = jax.jit(eval_body)

    return TrainSetup(
        model=model,
        state=state,
        train_step=train_step,
        eval_step=eval_step,
        code=code if cfg.approach in ("cyclic", "approx") else rep_code,
        unravel=unravel,
        dim=dim,
        train_many=train_many,
        metric_names=metric_names,
    )


# ---- program-lint registration (draco_tpu/analysis) -----------------------


def lint_programs():
    """The coded-DP CNN chip-bound programs and their manifests.

    Both execution shapes register: the eager ``train_step`` and the K-fused
    ``train_many`` scan (the production chunked loop's program,
    trainer._run_chunked). No explicit collectives: the (n, d) gradient
    gather is GSPMD-deferred (with_sharding_constraint only), so the
    manifest pins all-zero counts — an explicit collective appearing here
    would mean a shard_map/ppermute crept into the CNN path.
    """
    from draco_tpu.analysis.registry import (
        BF16_DTYPES, DEFAULT_DTYPES, BuiltProgram, LintProgram, Manifest,
    )
    from draco_tpu.parallel.partition import CNN_STEP_RULES

    def _cfg(**overrides):
        kw = dict(
            network="LeNet", dataset="synthetic-mnist", approach="cyclic",
            batch_size=2, num_workers=8, worker_fail=1, err_mode="rev_grad",
            lr=0.01, momentum=0.9, max_steps=3, eval_freq=0, train_dir="",
            log_every=10 ** 9,
        )
        kw.update(overrides)
        return TrainConfig(**kw)

    def _build(name, cfg, many=False, k=2, bf16=False, require=()):
        from draco_tpu import rng as drng, runtime

        mesh = runtime.make_mesh(cfg.num_workers)
        setup = build_train_setup(cfg, mesh)
        n, b = cfg.num_workers, cfg.batch_size
        shape = input_shape(cfg.dataset)
        adv = drng.adversary_schedule(cfg.seed, k + 1, n,
                                     cfg.num_adversaries)
        # the bf16 shadow/real wire's converts are whitelisted promotion
        # sites; those programs carry bf16 element types by design
        # (ISSUES 10/15). ``require``: the narrow-wire manifests PIN their
        # wire dtype in the module (rules.rule_dtype required_dtypes)
        manifest = Manifest(collectives={}, collective_axes={},
                            allowed_dtypes=(BF16_DTYPES if bf16
                                            else DEFAULT_DTYPES),
                            required_dtypes=frozenset(require))
        extra = {"dim": setup.dim, "devices_in_mesh": int(mesh.devices.size)}
        if many:
            args = (setup.state,
                    jnp.zeros((k, n, b) + shape, jnp.float32),
                    jnp.zeros((k, n, b), jnp.int32),
                    jnp.asarray(np.asarray(adv[1:k + 1])), None)
            return BuiltProgram(name, setup.train_many, args, mesh, manifest,
                                extra=extra,
                                partition_rules=CNN_STEP_RULES,
                                arg_names=("state", "x", "y", "adv_mask",
                                           "present"))
        args = (setup.state, jnp.zeros((n, b) + shape, jnp.float32),
                jnp.zeros((n, b), jnp.int32), jnp.asarray(np.asarray(adv[1])))
        return BuiltProgram(name, setup.train_step, args, mesh, manifest,
                            extra=extra, partition_rules=CNN_STEP_RULES,
                            arg_names=("state", "x", "y", "adv_mask"))

    mk = lambda name, fast=True, **kw: LintProgram(  # noqa: E731
        name=name, route="cnn", fast=fast,
        build=lambda name=name, kw=kw: _build(name, **kw))
    return [
        mk("cnn_cyclic_step", cfg=_cfg()),
        mk("cnn_cyclic_many_k2", cfg=_cfg(), many=True),
        # the repetition-vote path (group_size=4 >= 2s+1, n % r == 0)
        mk("cnn_majvote_step", cfg=_cfg(approach="maj_vote", group_size=4)),
        # the guarded production program (ISSUE 6): the in-graph step guard
        # must keep the manifest green — still zero explicit collectives,
        # full state donation, no host traffic (the guard is selects +
        # reductions, never a callback)
        mk("cnn_cyclic_many_guard_k2", cfg=_cfg(step_guard="on"),
           many=True),
        # the approximate family (coding/approx.py; ISSUE 8): same manifest
        # discipline — the optimal-decoding least squares and the
        # residual-vs-bound health columns must compile to pure GSPMD
        # (zero explicit collectives), keep full state donation and emit
        # zero host traffic, like every other chip-bound program
        mk("cnn_approx_step",
           cfg=_cfg(approach="approx", worker_fail=0, redundancy="shared",
                    code_redundancy=1.5)),
        mk("cnn_approx_many_guard_k2",
           cfg=_cfg(approach="approx", worker_fail=0, redundancy="shared",
                    code_redundancy=1.5, step_guard="on"),
           many=True),
        # shadow-watch programs (obs/numerics.py, ISSUE 10): the numerics
        # columns + shadow-quantized decode must keep every invariant —
        # zero explicit collectives, full state donation, zero host traffic
        # (reductions + a second decode, never a callback). The bf16 shadow
        # carries bf16 element types by design (BF16_DTYPES manifest, the
        # converts are the whitelisted promotion sites); the int8 shadow
        # stores its levels in f32 (numerics.quantize_rows docstring) and
        # its stochastic-rounding PRNG is plain ui32 bit generation.
        mk("cnn_cyclic_many_shadow_k2",
           cfg=_cfg(numerics_watch="on", shadow_wire="bf16"),
           many=True, bf16=True),
        mk("cnn_approx_shadow_int8_step",
           cfg=_cfg(approach="approx", worker_fail=0, redundancy="shared",
                    code_redundancy=1.5, numerics_watch="on",
                    shadow_wire="int8", shadow_round="stochastic")),
        # REAL narrow-wire production programs (ISSUE 15): the codewords
        # cross the sharding boundary as actual bf16 / int8(+f32 scale)
        # buffers, widened only inside the decode — every invariant holds
        # (zero explicit collectives, full donation, zero host traffic)
        # AND the manifest REQUIRES the narrow element type in the module
        # (required_dtypes): a silently-f32 "narrow" program trips the
        # dtype rule (control_wide_narrow_wire is the live negative
        # control). The bf16 row runs the λ-regularized locator +
        # quantization-aware threshold on the K-fused scan; the int8 row
        # adds stochastic shared-draw rounding on the approx family.
        mk("cnn_cyclic_wire_bf16_many_k2",
           cfg=_cfg(wire_dtype="bf16", step_guard="on"),
           many=True, bf16=True, require=("bf16",)),
        mk("cnn_approx_wire_int8_step",
           cfg=_cfg(approach="approx", worker_fail=0, redundancy="shared",
                    code_redundancy=1.5, wire_dtype="int8",
                    shadow_round="stochastic"),
           require=("i8",)),
        # fused-decode production programs (ISSUE 12): decode_impl="pallas"
        # resolves to the kernels' fused reference lowering on this CPU
        # host (ops/decode_kernels.resolve_decode_impl) — a plain XLA
        # program that must stay green under all six rules exactly like
        # the xla-path rows (zero explicit collectives, full donation,
        # zero host traffic, no big constants: the per-layer recombination
        # assembles from slices, never a d-length id constant). The
        # layer-granularity pair is the kernel's home regime and the
        # device-profile cells' join rows (tools/device_profile.py
        # cnn_cyclic_layer_* cells). fast=False: impl VARIANTS of
        # already-fast-swept step bodies — the full tool covers them (the
        # committed-artifact coverage test pins their presence) without
        # growing the per-commit --fast sweep budget.
        mk("cnn_cyclic_layer_step", cfg=_cfg(decode_granularity="layer"),
           fast=False),
        mk("cnn_cyclic_layer_pallas_step",
           cfg=_cfg(decode_granularity="layer", decode_impl="pallas"),
           fast=False),
        mk("cnn_approx_pallas_step",
           cfg=_cfg(approach="approx", worker_fail=0, redundancy="shared",
                    code_redundancy=1.5, decode_impl="pallas"),
           fast=False),
        # segmented-wire production programs (ISSUE 16): wire_segments=2
        # splits the decode into per-segment syndrome/locator/recombine
        # passes (coding/*.decode_segments) folded to ONE per-step verdict
        # — still a single jitted program obeying all six rules (zero
        # explicit collectives, full donation, zero host traffic, no
        # d-length constants: the segment assembly is dynamic_update_slice
        # over computed slices). Registered in both wire widths: the f32
        # pair pins the plain segmented decode; the narrow pair pins that
        # the segment slicing composes with the real bf16/int8 codeword
        # buffers (required_dtypes still enforced — segmentation must not
        # silently widen the wire). fast=False: S-variants of
        # already-fast-swept step bodies, covered by the full tool.
        mk("cnn_cyclic_seg2_many_k2",
           cfg=_cfg(wire_segments=2, step_guard="on"),
           many=True, fast=False),
        mk("cnn_cyclic_seg2_wire_bf16_many_k2",
           cfg=_cfg(wire_segments=2, wire_dtype="bf16", step_guard="on"),
           many=True, bf16=True, require=("bf16",), fast=False),
        mk("cnn_approx_seg2_step",
           cfg=_cfg(approach="approx", worker_fail=0, redundancy="shared",
                    code_redundancy=1.5, wire_segments=2),
           fast=False),
        mk("cnn_approx_seg2_wire_int8_step",
           cfg=_cfg(approach="approx", worker_fail=0, redundancy="shared",
                    code_redundancy=1.5, wire_segments=2,
                    wire_dtype="int8", shadow_round="stochastic"),
           require=("i8",), fast=False),
        # hierarchical tree production programs (ISSUE 17): topology="tree"
        # partitions the worker axis into n/g leaf groups of constant
        # fan-in, each running the ONE shared small code; decoded partials
        # combine level-structured IN-GRAPH (reshape+sum — algebraically
        # the per-level psum tree, still zero explicit collectives on the
        # GSPMD production route; the explicit shard_map tree form with its
        # pinned per-level all_reduce counts registers from
        # coding/topology.lint_programs). Same six-rule discipline; the
        # narrow-wire tree row pins that the per-group (g, d) wire blocks
        # keep the real bf16 buffers (required_dtypes). fast=False:
        # topology variants of already-fast-swept step bodies.
        mk("cnn_cyclic_tree_g4_step",
           cfg=_cfg(topology="tree", tree_fanout=4, adversary_count=0,
                    redundancy="shared"),
           fast=False),
        mk("cnn_cyclic_tree_g4_many_k2",
           cfg=_cfg(topology="tree", tree_fanout=4, adversary_count=0,
                    redundancy="shared", step_guard="on"),
           many=True, fast=False),
        mk("cnn_cyclic_tree_g4_wire_bf16_many_k2",
           cfg=_cfg(topology="tree", tree_fanout=4, adversary_count=0,
                    redundancy="shared", wire_dtype="bf16",
                    step_guard="on"),
           many=True, bf16=True, require=("bf16",), fast=False),
        mk("cnn_approx_tree_g4_step",
           cfg=_cfg(approach="approx", worker_fail=0, redundancy="shared",
                    code_redundancy=2.0, assignment_scheme="pairwise",
                    topology="tree", tree_fanout=4),
           fast=False),
    ]
