"""Standalone checkpoint-polling evaluator (reference:
src/distributed_evaluator.py — a separate process that watches train_dir over
NFS for ``model_step_k`` files every 10 s and reports top-1/top-5).

  python -m draco_tpu.training.evaluator --network LeNet --dataset MNIST \\
      --train-dir ./train_out/ --eval-freq 50
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np


def evaluate_params(model, params, batch_stats, xs, ys, batch_size=1000):
    n = len(xs)
    bs = min(batch_size, n)
    p1s, p5s = [], []
    vs = {"params": params}
    if batch_stats is not None:
        vs["batch_stats"] = batch_stats

    @jax.jit
    def _eval(x, y):
        logits = model.apply(vs, x, train=False)
        top1 = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
        top5 = jnp.mean(
            jnp.any(jax.lax.top_k(logits, 5)[1] == y[:, None], axis=1).astype(jnp.float32)
        )
        return top1, top5

    for i in range(0, n - bs + 1, bs):
        p1, p5 = _eval(jnp.asarray(xs[i : i + bs]), jnp.asarray(ys[i : i + bs]))
        p1s.append(float(p1))
        p5s.append(float(p5))
    return float(np.mean(p1s)), float(np.mean(p5s))


def main(argv=None):
    from draco_tpu.cli import add_fit_args, config_from_args, maybe_force_cpu_mesh
    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.runtime import make_mesh
    from draco_tpu.training.step import build_train_setup
    from draco_tpu.utils import checkpoint as ckpt

    parser = add_fit_args(argparse.ArgumentParser(description="draco_tpu evaluator"))
    parser.add_argument("--poll-seconds", type=float, default=10.0,
                        help="poll interval (reference sleeps 10 s, "
                        "distributed_evaluator.py:90)")
    parser.add_argument("--once", action="store_true", help="evaluate what exists, exit")
    args = parser.parse_args(argv)
    maybe_force_cpu_mesh(args)
    cfg = config_from_args(args)

    ds = load_dataset(cfg.dataset, cfg.data_dir)
    mesh = make_mesh(cfg.num_workers)
    setup = build_train_setup(cfg, mesh, dataset_name=ds.name)

    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), jax.device_get(setup.state)
    )
    seen = set()
    while True:
        for step in ckpt.available_steps(cfg.train_dir):
            if step in seen:
                continue
            state = ckpt.load(cfg.train_dir, step, abstract)
            stats = (
                jax.tree.map(lambda t: t[0], state.batch_stats)
                if state.batch_stats is not None
                else None
            )
            p1, p5 = evaluate_params(setup.model, state.params, stats,
                                     ds.test_x, ds.test_y, cfg.test_batch_size)
            print(f"Testset Performance: Cur Step:{step} Prec@1: {p1:.4f} Prec@5: {p5:.4f}",
                  flush=True)
            seen.add(step)
        if args.once:
            break
        time.sleep(args.poll_seconds)


if __name__ == "__main__":
    main()
