"""Standalone checkpoint-polling evaluator (reference:
src/distributed_evaluator.py — a separate process that watches train_dir over
NFS for ``model_step_k`` files every 10 s and reports top-1/top-5).

  python -m draco_tpu.training.evaluator --network LeNet --dataset MNIST \\
      --train-dir ./train_out/ --eval-freq 50
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np


def masked_full_split_eval(count_fn, xs, ys, batch_size):
    """Accuracy over ALL n samples: fixed-shape batches, with the ragged
    final batch padded up to the compiled shape and masked out of the counts
    (the pre-r4 loop dropped the n % bs tail). ``count_fn(x, y, valid) ->
    (correct@1 count, correct@5 count)`` over the valid mask. Shared by
    Trainer.evaluate and the checkpoint-polling evaluator so the pad/mask
    edge cases live in exactly one place.

    Deliberate deviation from the reference: the reference also covers the
    full split but averages *per-batch accuracies*
    (prec_counter / batch_counter, distributed_evaluator.py:105-107), which
    overweights a ragged final batch; this implementation sums correct
    counts and divides by n — exact sample-weighted accuracy. The two differ
    whenever n % bs != 0, so numbers here can legitimately diverge from the
    reference's by up to ~bs/n of the tail-batch accuracy gap."""
    n = len(xs)
    if n == 0:
        return 0.0, 0.0
    bs = min(batch_size, n)
    c1 = c5 = 0.0
    for i in range(0, n, bs):
        x = np.asarray(xs[i : i + bs])
        y = np.asarray(ys[i : i + bs])
        k = len(x)
        if k < bs:
            x = np.concatenate([x, np.repeat(x[:1], bs - k, axis=0)])
            y = np.concatenate([y, np.repeat(y[:1], bs - k, axis=0)])
        p1, p5 = count_fn(x, y, np.arange(bs) < k)
        c1 += float(p1)
        c5 += float(p5)
    return c1 / n, c5 / n


def evaluate_params(model, params, batch_stats, xs, ys, batch_size=1000):
    vs = {"params": params}
    if batch_stats is not None:
        vs["batch_stats"] = batch_stats

    @jax.jit
    def _count(x, y, valid):
        logits = model.apply(vs, x, train=False)
        ok1 = (jnp.argmax(logits, -1) == y) & valid
        ok5 = jnp.any(jax.lax.top_k(logits, 5)[1] == y[:, None], axis=1) & valid
        return jnp.sum(ok1.astype(jnp.float32)), jnp.sum(ok5.astype(jnp.float32))

    return masked_full_split_eval(_count, xs, ys, batch_size)


def main(argv=None):
    from draco_tpu.cli import add_fit_args, config_from_args, maybe_force_cpu_mesh
    from draco_tpu.data.datasets import load_dataset
    from draco_tpu.runtime import make_mesh
    from draco_tpu.training.step import build_train_setup
    from draco_tpu.utils import checkpoint as ckpt

    parser = add_fit_args(argparse.ArgumentParser(description="draco_tpu evaluator"))
    parser.add_argument("--poll-seconds", type=float, default=10.0,
                        help="poll interval (reference sleeps 10 s, "
                        "distributed_evaluator.py:90)")
    parser.add_argument("--once", action="store_true", help="evaluate what exists, exit")
    args = parser.parse_args(argv)
    maybe_force_cpu_mesh(args)
    cfg = config_from_args(args)

    ds = load_dataset(cfg.dataset, cfg.data_dir)
    mesh = make_mesh(cfg.num_workers)
    setup = build_train_setup(cfg, mesh, dataset_name=ds.name)

    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), jax.device_get(setup.state)
    )
    seen = set()
    while True:
        for step in ckpt.available_steps(cfg.train_dir):
            if step in seen:
                continue
            state = ckpt.load(cfg.train_dir, step, abstract)
            stats = (
                jax.tree.map(lambda t: t[0], state.batch_stats)
                if state.batch_stats is not None
                else None
            )
            p1, p5 = evaluate_params(setup.model, state.params, stats,
                                     ds.test_x, ds.test_y, cfg.test_batch_size)
            print(f"Testset Performance: Cur Step:{step} Prec@1: {p1:.4f} Prec@5: {p5:.4f}",
                  flush=True)
            seen.add(step)
        if args.once:
            break
        time.sleep(args.poll_seconds)


if __name__ == "__main__":
    main()
