"""Experiment configuration.

Knob parity with the reference CLI (reference: src/distributed_nn.py:23-77 and
src/single_machine.py:27-54), folded into one dataclass instead of per-entry
argparse. Quirks intentionally dropped: ``--comm-type`` (admitted fake,
reference README.md:111), ``--num-aggregate`` (unused, distributed_nn.py:60).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

# Deterministic seed shared by every participant, mirroring the reference's
# global SEED_=428 (reference: src/util.py:17). Every device derives the
# adversary schedule / group seeds / shuffles from this, so all agree.
SEED = 428

# Aggregation modes for approach=baseline. First three mirror the reference
# (baseline_master.py:118-129); the rest are beyond-reference robust
# baselines (aggregation.py). Lives here (jax-free) so the CLI's --mode
# choices and validate() share one source of truth.
AGG_MODES = ("normal", "geometric_median", "krum", "coord_median",
             "trimmed_mean", "multi_krum", "bulyan")


@dataclasses.dataclass
class TrainConfig:
    # --- model / data (reference: distributed_nn.py:27-37) ---
    network: str = "LeNet"  # LeNet | FC | ResNet18/34/50/101/152 | VGG11/13/16/19[_bn]
    dataset: str = "MNIST"  # MNIST | Cifar10 | synthetic variants
    data_dir: str = "./data"
    batch_size: int = 128  # per-worker batch size
    test_batch_size: int = 1000

    # --- optimization (reference: distributed_nn.py:31-43) ---
    optimizer: str = "sgd"  # sgd | adam (reference parity) | adamw (decoupled decay)
    lr: float = 0.01
    momentum: float = 0.9
    weight_decay: float = 0.01  # adamw's decoupled decay (unused by sgd/adam)
    lr_schedule: str = "constant"  # constant | cosine (warmup + cosine to 10%)
    warmup_steps: int = 0  # linear warmup length for lr_schedule=cosine
    clip_norm: float = 0.0  # >0: global-norm clip of the aggregated gradient
    max_steps: int = 10000

    # --- distributed topology ---
    num_workers: int = 8  # n logical workers = size of mesh axis `w`
    # Approach selects the training runtime, mirroring --approach
    # (reference: distributed_nn.py:87-133):
    #   baseline : plain data parallel + robust aggregation per `mode`
    #   maj_vote : repetition code, groups of size `group_size`, majority vote
    #   cyclic   : cyclic (DFT) code, tolerates s Byzantine workers
    #   approx   : approximate gradient code (coding/approx.py) — straggler
    #              tolerance at fractional redundancy `code_redundancy`
    #              close to 1, bounded decode error instead of exactness
    approach: str = "baseline"
    # Aggregation mode for approach=baseline. Reference parity
    # (baseline_master.py:118-129): normal | geometric_median | krum.
    # Beyond-reference robust baselines under the same attack schedules:
    # coord_median | trimmed_mean | multi_krum | bulyan (aggregation.py).
    mode: str = "normal"
    group_size: int = 3  # r, repetition redundancy (reference: distributed_nn.py:70)
    # maj_vote row-equality check: "fingerprint" = O(r·d) salted-hash vote
    # (per-step key, sound unless adversaries know the experiment seed);
    # "exact" = O(r²·d) full pairwise bit-equality, the reference's
    # exact-recovery semantics (rep_master.py:162) with no collision
    # surface — pick it for mutually-untrusting deployments
    # (coding/repetition.py module docstring, threat-model ladder).
    vote_check: str = "fingerprint"
    worker_fail: int = 0  # s, number of Byzantine workers (distributed_nn.py:68)

    # --- approximate code family (approach="approx"; ISSUE 8) ---
    # Computational redundancy r ∈ [1, n]: each worker computes ~r batches
    # (exact codes pay r = 2s+1). Fractional r mixes ⌊r⌋/⌊r⌋+1 loads
    # (coding/assignment.py); the decode error under drops is bounded by
    # the optimal-decoding least squares (coding/approx.py docstring).
    code_redundancy: float = 1.5
    # Straggler design point: the decode is dimensioned for up to
    # ⌈straggler_alpha · n⌉ absent workers per step — validate() holds
    # straggle_count to it, and tools/straggler_study.py sweeps it.
    straggler_alpha: float = 0.25
    # Batch-to-worker assignment: "pairwise" (pair-wise balanced cyclic
    # windows, any r) or "clustered" (fractional repetition, integer r
    # dividing n — any one survivor per cluster keeps the decode exact).
    assignment_scheme: str = "pairwise"

    # --- adversary simulation (reference: distributed_nn.py:64-67) ---
    err_mode: str = "rev_grad"  # rev_grad | constant | random | alie | ipm
    adversarial: float = -100.0  # attack magnitude (model_ops/utils.py:3-4)

    # --- straggler simulation (TPU-native; supersedes the reference's
    # unreferenced tag-77 kill switch, resnet_split.py:625-737) ---
    # "none": every gradient arrives. "drop": straggle_count workers per step
    # miss the deadline; their rows are treated as *erasures* (known-missing):
    # cyclic decodes around them (up to 2s erasure-only, or jointly with
    # adversaries when straggle_count + worker_fail <= s), maj_vote votes
    # among present members, baseline aggregates over present rows.
    straggle_mode: str = "none"  # none | drop
    straggle_count: int = 0
    # Actual adversaries injected per step. None = worker_fail (reference
    # semantics: the code parameter s doubles as the live attack count,
    # distributed_nn.py:68). Set lower to reserve locator budget for
    # stragglers (joint regime: adversary_count + straggle_count <= worker_fail).
    adversary_count: Optional[int] = None

    # --- coded-path execution strategy (TPU-native addition) ---
    # "simulate": every worker really computes its (2s+1) redundant batches,
    #             matching the reference's r× compute cost (cyclic_worker.py:122).
    # "shared":   each distinct batch gradient is computed once on the mesh and
    #             encoded rows are formed algebraically — identical semantics
    #             (per-batch gradients are deterministic), r× less compute.
    redundancy: str = "simulate"
    # Decode granularity: "global" locates the corrupt-row set once on the
    # flattened gradient (valid: corruption is per-worker, shared by layers);
    # "layer" re-runs the locator per layer like the reference
    # (cyclic_master.py:126-128).
    decode_granularity: str = "global"
    # Decode implementation (ISSUE 12; ops/decode_kernels.py). "auto":
    # the fused Pallas decode kernels on TPU backends, the historical XLA
    # lowering elsewhere — CI and CPU runs keep today's bitwise path.
    # "xla": pin the historical lowering everywhere. "pallas": the fused
    # kernels where a TPU can run them, their reference lowering (the
    # same fused algorithm through XLA — bounded-err vs xla, identical
    # honest/flag sets) on other backends. Applies to the cyclic locator
    # chain and the approx partial-recovery decode on every route; the
    # shadow-quantized decode (obs/numerics.py) stays on the xla path its
    # thresholds were calibrated on.
    decode_impl: str = "auto"

    # --- long context / sequence parallelism (TPU-native addition; the
    # reference is CNN-only, SURVEY.md §5.7) ---
    seq_shards: int = 1  # sp mesh-axis size; sequence parallelism spans these
    # SP strategy: "ring" streams K/V blocks over ppermute hops (O(T·T/sp)
    # peak scores, sp hops); "a2a" is Ulysses-style head-scatter all_to_all
    # (2 collectives total, needs model_heads % sp == 0, materialises the
    # full (T,T) score block per head group)
    sp_attn: str = "ring"
    # Single-shard attention implementation (seq_shards == 1): "dense"
    # materialises (T, T) scores per head; "flash" is the Pallas blockwise
    # kernel (ops/flash_attention.py) — O(T·Dh) memory, for long sequences
    # on one chip. Off-TPU it falls back to dense automatically.
    attn_impl: str = "dense"
    # tp mesh-axis size for the GSPMD tensor-parallel path (parallel/
    # tp_step.py); composes with the coded worker axis on a (w, tp) mesh
    tensor_shards: int = 1
    # Switch-MoE: experts per block (0 = dense MLP) and the ep mesh-axis
    # size sharding the expert stacks (parallel/ep_step.py, models/moe.py)
    moe_experts: int = 0
    expert_shards: int = 1
    # pp mesh-axis size for the GPipe-style pipeline path (parallel/
    # pp_step.py): transformer blocks split into pipeline_shards stages,
    # microbatches flow stage-to-stage over ppermute hops
    pipeline_shards: int = 1
    # microbatches per step for the pipeline schedule (0 = pipeline_shards);
    # more microbatches shrink the bubble: S-1 of M+S-1 ticks are idle
    pp_microbatches: int = 0
    seq_len: int = 256  # tokens per sequence (global, pre-sharding)
    vocab: int = 256
    model_dim: int = 128
    model_heads: int = 4
    model_layers: int = 2

    # --- precision ---
    compute_dtype: str = "float32"  # forward/backward dtype (bfloat16|float32)

    # --- eval / checkpoint (reference: distributed_nn.py:56-75) ---
    eval_freq: int = 50
    train_dir: str = "./train_out/"
    # operator-facing job label stamped into status.json (STATUS_SCHEMA
    # 5, obs/heartbeat.py) — purely observational: the fleet registry
    # (obs/fleet.py) groups/labels runs by it. "" omits the field.
    job_name: str = ""
    # resume from this step if >0; -1 resumes from the NEWEST loadable
    # checkpoint in train_dir (corrupt ones are skipped — the automatic
    # walk-back of resilience/supervisor.restore_with_walkback)
    checkpoint_step: int = 0
    # write checkpoints as shuffled-deflate .dcg archives instead of Orbax
    # dirs — the descendant of the reference's --compress-grad wire toggle
    # (compress_gradient.py:7-15), for train_dirs crossing a slow link.
    # Single-host only (utils/checkpoint.py).
    compress_ckpt: bool = False

    # --- host-loop fusion (TPU-native addition; PERF.md §0/§4b) ---
    # K training steps fused into ONE jitted lax.scan per device program
    # (training/step.py train_many for the coded-DP CNN Trainer;
    # parallel/common.py make_token_train_many + parallel/token_loop.py for
    # every TransformerLM route — single-shard, sp, tp, pp, ep): the host
    # dispatches once per K steps and fetches one (K, m) metrics block
    # instead of per-step scalars, hiding the ~70 ms/dispatch RTT of remote
    # backends behind useful work. Eval/checkpoint cadence snaps to chunk
    # boundaries (explicit remainder chunks, so max_steps need not divide
    # by K). K=1 keeps today's eager per-step loop bit-for-bit. CPU caveat:
    # XLA:CPU runs conv thunks inside scan bodies single-threaded
    # (PERF.md §4), so the default stays 1 for conv nets — raise it on
    # accelerators (and freely for the matmul-dominated TransformerLM /
    # FC, where the caveat does not apply — PERF.md §4b).
    steps_per_call: int = 1
    # Where the synthetic token stream is generated (TransformerLM routes):
    # "host" — numpy synthetic_text per step, uploaded per step/chunk (the
    # historical stream); "device" — the chunked driver regenerates each
    # step's batch in-graph from the scalar (seed, step)
    # (sp_step.synthetic_text_in_graph), so a chunk's upload is K int32
    # scalars and the host token path disappears. The two streams are
    # distinct deterministic draws (jax PRNG vs numpy MT19937); either is
    # internally bitwise-reproducible across K.
    token_gen: str = "host"

    # rematerialise activations in backward (jax.checkpoint) — memory for FLOPs
    remat: bool = False
    # compile the LM's layer stack as one nn.scan over stacked block weights
    # instead of `model_layers` unrolled block programs — identical math,
    # ~layers× smaller XLA program (keeps deep/large configs under
    # compile-time ceilings). LM paths only; changes the params tree layout
    # (one stacked "blocks" subtree), so checkpoints don't interchange with
    # the unrolled form.
    scan_layers: bool = False

    # --- telemetry (draco_tpu/obs; ISSUE 4) ---
    # When set, the production loops write a Chrome-trace-event
    # ``trace_dir/trace.json`` of the HOST phases the chunked regime
    # otherwise hides (gather/upload/dispatch/sync/flush/eval/ckpt +
    # prefetcher worker-thread lanes + queue-depth counters) — open it in
    # chrome://tracing or https://ui.perfetto.dev. Disabled (the default)
    # the tracer is a shared no-op object: no allocation, no clock reads,
    # and never any device fetch either way. Device-side phase attribution
    # is the separate jax.profiler capture (--profile-dir), aligned via the
    # jax.named_scope phase names inside the step programs.
    trace_dir: str = ""
    # Compile/retrace sentinel (obs/compile_watch.py; ISSUE 5). Every XLA
    # executable build is recorded in ``compiles.jsonl`` (next to trace.json
    # when trace_dir is set, else next to metrics.jsonl) and surfaced in the
    # status.json heartbeat. After ``compile_warmup`` builds per registered
    # program (per chunk shape), any further build is a steady-state
    # recompilation — it silently re-pays the multi-second compile the
    # scan-chunk design exists to amortize. compile_guard: "warn" (default)
    # emits RetraceWarning, "raise" fails the dispatch (the test/CI mode the
    # K∈{1,4} equivalence suites run under), "off" records only.
    compile_guard: str = "warn"
    compile_warmup: int = 1
    # Numerics observatory (obs/numerics.py; ISSUE 10). "on" adds per-step
    # dynamic-range columns (absmax / rms / bf16- and int8-threshold
    # underflow-overflow fractions / exponent histogram) for the pre-encode
    # gradients, the post-encode codewords, and the decoded aggregate,
    # riding the existing (K, m) metric block — zero extra device fetches,
    # zero retraces. Coded approaches only (cyclic / maj_vote / approx):
    # the baseline path ships no codewords and emits no optional columns.
    numerics_watch: str = "off"
    # --- the REAL narrow coded wire (obs/numerics.py; ISSUE 15) ---
    # What the worker→aggregator wire PHYSICALLY carries. "f32" keeps
    # today's wire bit-for-bit (no ops added). "bf16"/"int8": the step
    # body rounds the codewords into REAL narrow buffers (bf16 casts;
    # int8 with per-block scales over shadow_block elements and — under
    # shadow_round="stochastic" — shared-draw stochastic rounding) which
    # cross the worker-sharding boundary narrow and are widened to f32
    # only inside the decode (f32 accumulation throughout): the 2–4×
    # wire-bytes/HBM win of PERF.md §13's ledger, landed on the actual
    # coded path. The cyclic decode then runs the quantization-aware flag
    # threshold (per-(n, s, dtype) table derived by tools/wire_study.py)
    # and the Tikhonov-regularized locator (λ scaled to the dtype's noise
    # floor — the PR 10 large-n blocker's fix); the step guard and the
    # decode_residual incident detector widen their tolerances by the
    # dtype's residual slack. Coded approaches only; mutually exclusive
    # with shadow_wire (the shadow is the CALIBRATION mode — it measures
    # a candidate dtype against the f32 wire, which a narrow wire no
    # longer ships).
    wire_dtype: str = "f32"  # f32 | bf16 | int8
    # --- streaming segmented wire (ISSUE 16; ROADMAP item 3) ---
    # Split the d dimension of the coded wire into this many segments:
    # workers emit per-segment codeword buffers (narrow under wire_dtype,
    # with per-segment int8 block scales) and the aggregator decodes each
    # segment as it arrives instead of waiting for the full (n, d) wire —
    # the arXiv:1903.01974 multi-message communication pattern. 1 (the
    # default) keeps today's single-message wire bit-for-bit. S > 1 cuts
    # at multiples of the segment quantum (obs/numerics.wire_segment_bounds:
    # TILE_D when d admits it, else shadow_block), which keeps the int8
    # per-block scales and the shared stochastic-rounding draws segment-
    # invariant — quantize-then-slice equals slice-then-quantize bitwise,
    # so the narrow buffers are unchanged and only the DECODE is
    # segmented. Syndromes and located-row sets are computed per segment;
    # the health/forensics columns fold across segments (residual = max,
    # flagged/loud = union) so guards, detection P/R, incidents and the
    # autopilot see one verdict per step. Coded approaches (cyclic/approx)
    # only; d smaller than the quantum collapses back to one segment.
    wire_segments: int = 1
    # --- hierarchical CodedReduce aggregation (ISSUE 17; ROADMAP item 2) ---
    # topology="tree" partitions the (n,) worker axis into n/tree_fanout
    # leaf groups of constant fan-in g (coding/topology.py — the
    # clustered-assignment window algebra); each group runs its OWN small
    # code (cyclic at s_g = min(worker_fail, (g-1)//4), capped further by
    # the per-(g, s, dtype) narrow-wire threshold table; approx at the
    # configured fractional redundancy), decodes locally, and parents
    # combine decoded (d,) partials level by level — per-node decode cost
    # and ingest bytes stay O(g·d) as n grows (arXiv:1902.01981). The
    # per-group health verdicts fold to one per-step verdict exactly like
    # the wire-segment fold (residual=max, flagged/accused=union), so
    # detection P/R is identical to flat. Coded families only
    # (cyclic/approx, shared redundancy, global decode granularity);
    # composes with wire_dtype and wire_segments.
    topology: str = "flat"  # flat | tree
    tree_fanout: int = 4  # leaf-group size g (must divide num_workers)
    # total tree levels including the leaf level; 0 = auto
    # (1 + ceil(log_g(n/g)), coding/topology.auto_levels)
    tree_levels: int = 0
    # Shadow-quantized wire (obs/numerics.py): round the codewords to the
    # narrow dtype INSIDE the step body, decode the shadow copy alongside
    # the f32 path, and emit shadow_err / shadow_residual /
    # shadow_flag_agree (+ shadow detection counts) columns. The f32 path
    # alone updates params — K∈{1,4} equivalence stays bitwise with the
    # shadow enabled. This is the measurement ROADMAP item 4's real
    # bf16/int8 wire will be built and regression-gated on.
    shadow_wire: str = "off"  # off | bf16 | int8
    # Shadow rounding mode: "nearest" (deterministic round-to-nearest) or
    # "stochastic" (per-step seeded noise, shared across wire rows so
    # bitwise-identical rows quantize identically — maj_vote's soundness
    # condition survives).
    shadow_round: str = "nearest"
    # int8 per-block scale granularity: one f32 scale per this many
    # elements along the wire row (also the blocking the numerics columns'
    # int8 underflow threshold uses).
    shadow_block: int = 256
    # Incident engine (obs/incidents.py; ISSUE 13). "on" folds the
    # per-step column families + the heartbeat beat extras into typed,
    # attributed run-health incidents (throughput regression, decode-
    # residual drift, trust collapse, guard budget burn, numerics drift,
    # compile storms, prefetch starvation) with onset/offset hysteresis —
    # streamed to train_dir/incidents.jsonl and the ``incidents`` block of
    # status.json (STATUS_SCHEMA 4). Host-side only: zero extra device
    # fetches, zero retraces, bitwise-transparent to training. Needs a
    # train_dir (the stream and the heartbeat live there). Any approach:
    # detectors silently skip column families the route does not emit.
    incident_watch: str = "off"
    # Per-detector threshold overrides, comma-separated
    # "<detector>.<key>=<float>" (e.g. "trust.floor=0.4,guard.off_count=2")
    # — keys validated against the declarative detector registry at config
    # time. "" keeps every registered default (PERF.md §15 table).
    incident_thresholds: str = ""

    # --- adaptive coding autopilot (draco_tpu/control; ROADMAP item 5) ---
    # "on": a host-side policy engine consumes the incident stream at
    # chunk boundaries and emits remediations — quarantine a
    # trust-collapsed worker (present-mask exclusion), dial exact cyclic
    # redundancy down to the approx family under sustained
    # straggle/starvation episodes (and back up on sustained clean
    # evidence), drop the shadow wire dtype on numerics_drift. Family
    # swaps are warm cached program swaps (0 steady retraces within a
    # regime); every decision is an attributed `remediation` event in
    # incidents.jsonl + a `control` status.json block. Requires
    # incident_watch="on" (the sensing layer), a train_dir, the chunked
    # regime (steps_per_call > 1 — chunk boundaries are the actuation
    # points; the LM device-token-gen driver runs chunked at any K), and
    # a cyclic/approx starting family.
    autopilot: str = "off"
    # "key=value,..." overrides of control.autopilot.DEFAULT_POLICY
    # (hysteresis boundary counts, trust floor, r_low, budgets) —
    # validated against the policy table at config time.
    autopilot_policy: str = ""

    # --- resilience (draco_tpu/resilience; ISSUE 6) ---
    # In-graph step guard: fold the decode-health signals (loud
    # decode_residual, located rows beyond the s budget, vote disagreement
    # past budget) with a global-finite check on the aggregated gradient
    # and SKIP the optimizer update via branchless carry passthrough when a
    # step is untrusted (resilience/guards.py). The guard emits
    # guard_trips/skipped_steps metric columns riding the existing (K, m)
    # block — zero extra device fetches, zero retraces (the guard is
    # config-static). "off" keeps today's unguarded update bit-for-bit;
    # "on" is bitwise identical on clean steps (jnp.where select) and the
    # bounded-degradation posture under faults the code does not model
    # (non-finite gradients from faulty-but-honest workers, beyond-budget
    # corruption — the Stochastic Gradient Coding framing, PAPERS.md).
    step_guard: str = "off"
    # decode_residual above this is "loud" (clean decodes sit at f32 solve
    # noise, ~1e-6 relative; a mislocated beyond-budget decode is O(1))
    guard_residual_tol: float = 1e-3
    # Deterministic fault-injection plan (resilience/faults.py): comma-
    # separated "kind@step[:w<worker>][:d<seconds>]" events, same seeded
    # discipline as the adversary schedules. In-graph kinds (nan_grad /
    # inf_grad / over_budget) corrupt the step inputs; host kinds
    # (prefetch_crash / prefetch_hang / sigterm) fire in the host loop.
    # "" (default) injects nothing and compiles the exact unfaulted
    # programs. tools/chaos_run.py drives the fault × loop matrix.
    fault_spec: str = ""
    # Bound on a worker-THREAD prefetch queue wait (seconds; 0 disables):
    # a dead/hung token-prefetch worker (TokenChunkPrefetcher — the one
    # prefetcher whose assembly runs user code on a thread) raises the
    # named PrefetchStallError instead of blocking the main loop forever
    # (data/prefetch.py). The CNN prefetchers' native row gather has no
    # bounded-wait API; its failures surface synchronously as exceptions,
    # which the same supervision retries.
    prefetch_timeout_s: float = 300.0
    # Bounded prefetcher supervision (resilience/supervisor.py): on a
    # worker-thread exception or stall the prefetcher is abandoned and
    # rebuilt with exponential backoff, up to this many restarts per
    # request before the error propagates. 0 disables supervision.
    prefetch_restarts: int = 2
    # Retain-last-N checkpoint GC (utils/checkpoint.py gc_checkpoints):
    # after each save, delete all but the newest N checkpoints in
    # train_dir. 0 (default) keeps everything (current behavior); GC never
    # deletes the newest checkpoint. N >= 2 leaves the corrupt-newest
    # walk-back (checkpoint_step=-1) an older checkpoint to fall back to.
    keep_checkpoints: int = 0

    # --- misc ---
    seed: int = SEED
    geomedian_iters: int = 80  # Weiszfeld iterations (replaces hdmedians dep)
    log_every: int = 10

    @property
    def s(self) -> int:
        return self.worker_fail

    @property
    def hat_s(self) -> int:
        """Batches per worker under the cyclic code (reference: cyclic_worker.py:29)."""
        return 2 * self.worker_fail + 1

    @property
    def num_groups(self) -> int:
        return self.num_workers // self.group_size

    @property
    def tree_group_fail(self) -> int:
        """Per-group cyclic error budget under topology='tree':
        min(worker_fail, (g-1)//4) — coding/topology.group_worker_fail."""
        from draco_tpu.coding.topology import group_worker_fail

        return group_worker_fail(self.tree_fanout, self.worker_fail)

    @property
    def num_adversaries(self) -> int:
        """Live adversaries per step (defaults to the code parameter s)."""
        return self.worker_fail if self.adversary_count is None else self.adversary_count

    def validate(self) -> "TrainConfig":
        if self.approach not in ("baseline", "maj_vote", "cyclic", "approx"):
            raise ValueError(f"unknown approach: {self.approach}")
        if self.approach == "baseline" and self.mode not in AGG_MODES:
            raise ValueError(
                f"baseline supports mode in {'|'.join(AGG_MODES)}, "
                f"got: {self.mode}"
            )
        if (self.mode in ("krum", "multi_krum", "bulyan")
                and self.num_workers < self.worker_fail + 3):
            raise ValueError(f"{self.mode} requires num_workers >= worker_fail + 3")
        if (self.mode in ("trimmed_mean", "bulyan")
                and self.num_workers <= 2 * self.worker_fail):
            raise ValueError(
                f"{self.mode} requires num_workers > 2 * worker_fail"
            )
        if self.lr_schedule not in ("constant", "cosine"):
            raise ValueError(f"unknown lr_schedule: {self.lr_schedule}")
        if self.warmup_steps < 0:
            raise ValueError(f"warmup_steps must be >= 0, got {self.warmup_steps}")
        if self.clip_norm < 0:
            raise ValueError(f"clip_norm must be >= 0, got {self.clip_norm}")
        if self.warmup_steps > 0 and self.lr_schedule == "constant":
            raise ValueError(
                "warmup_steps > 0 has no effect with lr_schedule=constant — "
                "set --lr-schedule cosine (or drop --warmup-steps)"
            )
        if self.err_mode not in ("rev_grad", "constant", "random",
                                 "alie", "ipm"):
            raise ValueError(f"unknown err_mode: {self.err_mode}")
        if self.err_mode in ("alie", "ipm") and self.approach == "cyclic":
            raise ValueError(
                f"err_mode={self.err_mode} targets approximate robust "
                f"aggregation (baseline modes / maj_vote); the cyclic path's "
                f"attack surface is the encoded rows, where decode is exact "
                f"and any per-row corruption is removed — use rev_grad/"
                f"constant there (attacks.py)"
            )
        if self.approach == "maj_vote":
            if self.vote_check not in ("fingerprint", "exact"):
                raise ValueError(
                    f"vote_check must be 'fingerprint' or 'exact', got "
                    f"{self.vote_check!r}"
                )
            if self.num_workers % self.group_size != 0:
                raise ValueError(
                    "maj_vote requires num_workers divisible by group_size "
                    f"(got {self.num_workers} % {self.group_size})"
                )
            if self.worker_fail > 0 and self.group_size < 2 * self.worker_fail + 1:
                # the repetition code's guarantee is r = 2s+1 (reference
                # README.md:9); with r < 2s+1 all s adversaries can land in one
                # group and break its majority
                raise ValueError(
                    f"maj_vote with worker_fail={self.worker_fail} requires "
                    f"group_size >= {2 * self.worker_fail + 1} (r = 2s+1)"
                )
        if self.approach == "cyclic" and self.topology == "flat":
            if self.num_workers <= 4 * self.worker_fail:
                # decode needs n-2s honest rows to span C1's n-2s columns and
                # the locator solve needs 2s syndrome equations
                raise ValueError(
                    f"cyclic code needs n > 4s (got n={self.num_workers}, s={self.worker_fail})"
                )
        if self.approach == "approx":
            if self.num_adversaries > 0:
                # the optimal-decoding weights average whatever arrives —
                # there is no error locator, so a single live Byzantine row
                # poisons the decode undetectably. Stragglers are this
                # family's fault model (coding/approx.py docstring).
                raise ValueError(
                    "approach=approx carries no Byzantine certificate: set "
                    "worker_fail=0 (or adversary_count=0 to keep worker_fail "
                    "as a nominal code parameter) — use cyclic/maj_vote for "
                    "live adversaries"
                )
            if self.redundancy != "shared":
                # fractional loads make the r×-redundant lanes ragged; the
                # shared encode is algebraically identical and is the whole
                # point of redundancy near 1
                raise ValueError(
                    "approach=approx requires redundancy='shared' (the "
                    "assignment's fractional loads have no fixed-lane "
                    "simulate shape)"
                )
            if not (1.0 <= self.code_redundancy <= self.num_workers):
                raise ValueError(
                    f"code_redundancy must lie in [1, num_workers], got "
                    f"{self.code_redundancy} at n={self.num_workers}"
                )
            if not (0.0 <= self.straggler_alpha < 1.0):
                raise ValueError(
                    f"straggler_alpha must lie in [0, 1), got "
                    f"{self.straggler_alpha}"
                )
            # construction-time errors (scheme name, clustered divisibility/
            # integrality) surface at config time, not mid-run
            from draco_tpu.coding.assignment import build_assignment

            build_assignment(self.num_workers, self.code_redundancy,
                             self.assignment_scheme)
        from draco_tpu.coding.topology import TOPOLOGIES, tree_plan

        if self.topology not in TOPOLOGIES:
            raise ValueError(
                f"topology must be one of {'|'.join(TOPOLOGIES)}, got "
                f"{self.topology!r}"
            )
        if self.topology == "tree":
            if self.approach not in ("cyclic", "approx"):
                raise ValueError(
                    "topology='tree' supports the algebraic code families "
                    f"(cyclic|approx), got approach={self.approach!r} — "
                    "maj_vote's repetition groups are already a one-level "
                    "tree of constant fan-in 2s+1"
                )
            if self.redundancy != "shared":
                raise ValueError(
                    "topology='tree' requires redundancy='shared': each "
                    "leaf group's code mixes its own batch rows in place "
                    "(the simulate lanes have no per-group shape)"
                )
            if self.decode_granularity != "global":
                raise ValueError(
                    "topology='tree' requires decode_granularity='global' "
                    "— the tree already partitions the locator per group; "
                    "per-layer cuts do not align with the per-group wire "
                    "blocks (compose with --wire-segments instead)"
                )
            if self.shadow_wire != "off":
                raise ValueError(
                    "topology='tree' composes with the REAL narrow wire "
                    "(--wire-dtype) but not the flat shadow decode "
                    "(--shadow-wire measures the FLAT locator's "
                    "quantization amplification; run it at topology='flat' "
                    "before narrowing, then ship the tree)"
                )
            # shape errors (divisibility, group count, level feasibility)
            # surface at config time
            tree_plan(self.num_workers, self.tree_fanout, self.tree_levels)
            if self.approach == "cyclic":
                s_g = self.tree_group_fail
                if self.num_adversaries > s_g:
                    # worst case every adversary lands in ONE leaf group
                    # (the schedules are independent): the small code must
                    # carry them alone
                    raise ValueError(
                        f"tree per-group budget exceeded: adversary_count="
                        f"{self.num_adversaries} > s_g={s_g} (= min("
                        f"worker_fail, (tree_fanout-1)//4) — raise "
                        f"tree_fanout past {4 * self.num_adversaries} or "
                        f"reduce the adversary load)"
                    )
        if self.worker_fail > self.num_workers:
            raise ValueError("worker_fail cannot exceed num_workers")
        if self.compute_dtype not in ("float32", "bfloat16"):
            raise ValueError(f"compute_dtype must be float32|bfloat16, got {self.compute_dtype}")
        if self.steps_per_call < 1:
            raise ValueError(
                f"steps_per_call must be >= 1, got {self.steps_per_call}"
            )
        if self.token_gen not in ("host", "device"):
            raise ValueError(
                f"token_gen must be host|device, got {self.token_gen}"
            )
        if self.token_gen == "device" and self.network != "TransformerLM":
            # the CNN Trainer trains on dataset rows, not a generated token
            # stream — there is nothing for the in-graph generator to replace
            raise ValueError(
                "token_gen='device' applies to the TransformerLM token "
                "routes only (the CNN Trainer reads dataset batches)"
            )
        from draco_tpu.obs.compile_watch import GUARD_MODES

        if self.compile_guard not in GUARD_MODES:
            raise ValueError(
                f"compile_guard must be one of {'|'.join(GUARD_MODES)}, "
                f"got {self.compile_guard!r}"
            )
        if self.compile_warmup < 0:
            raise ValueError(
                f"compile_warmup must be >= 0, got {self.compile_warmup}"
            )
        if self.numerics_watch not in ("off", "on"):
            raise ValueError(
                f"numerics_watch must be off|on, got {self.numerics_watch!r}"
            )
        if self.shadow_wire not in ("off", "bf16", "int8"):
            raise ValueError(
                f"shadow_wire must be off|bf16|int8, got {self.shadow_wire!r}"
            )
        if self.wire_dtype not in ("f32", "bf16", "int8"):
            raise ValueError(
                f"wire_dtype must be f32|bf16|int8, got {self.wire_dtype!r}"
            )
        if self.wire_dtype != "f32":
            if self.approach not in ("cyclic", "maj_vote", "approx"):
                # the narrow wire quantizes the CODED wire; the baseline
                # path ships raw rows to approximate robust rules with no
                # certificate to re-threshold — same rule as the shadow
                raise ValueError(
                    "wire_dtype != f32 requires a coded approach "
                    f"(cyclic|maj_vote|approx), got {self.approach!r}"
                )
            if self.shadow_wire != "off":
                raise ValueError(
                    "wire_dtype and shadow_wire are mutually exclusive: "
                    "the shadow is the calibration mode — it measures a "
                    "candidate dtype AGAINST the f32 wire, which a narrow "
                    "wire no longer ships (set shadow_wire=off, or keep "
                    "wire_dtype=f32 while calibrating)"
                )
            if self.approach == "cyclic":
                # shapes whose certificate still degrades under the
                # regularized locator must route through the approx family
                # (arXiv:1802.03475's communication-efficient coding) —
                # the committed threshold table is the contract
                from draco_tpu.obs.numerics import wire_rel_tol

                # tree decodes per GROUP: the threshold that matters is the
                # small code's shape (g, s_g), not (n, s)
                wn, ws = ((self.tree_fanout, self.tree_group_fail)
                          if self.topology == "tree"
                          else (self.num_workers, self.worker_fail))
                if not (wire_rel_tol(wn, ws, self.wire_dtype) < 1.0):
                    raise ValueError(
                        f"no usable narrow-wire flag threshold at "
                        f"(n={wn}, s={ws}, "
                        f"{self.wire_dtype}) — run tools/wire_study.py at "
                        f"this shape, or route the narrow wire through "
                        f"approach=approx (no locator to amplify the "
                        f"quantization noise)"
                    )
        if self.wire_segments < 1:
            raise ValueError(
                f"wire_segments must be >= 1, got {self.wire_segments}"
            )
        if self.wire_segments > 1 and self.approach not in (
                "cyclic", "maj_vote", "approx"):
            # segmentation slices the coded wire; the baseline path ships
            # raw rows with no decode to segment. (maj_vote's group-replica
            # vote is row-wise, not d-separable — its segmentation is
            # wire/ledger-only and the vote verdict is unchanged.)
            raise ValueError(
                "wire_segments > 1 requires a coded approach "
                f"(cyclic|maj_vote|approx), got {self.approach!r}"
            )
        if self.shadow_round not in ("nearest", "stochastic"):
            raise ValueError(
                f"shadow_round must be nearest|stochastic, got "
                f"{self.shadow_round!r}"
            )
        if self.shadow_block < 1:
            raise ValueError(
                f"shadow_block must be >= 1, got {self.shadow_block}"
            )
        if ((self.numerics_watch == "on" or self.shadow_wire != "off")
                and self.approach not in ("cyclic", "maj_vote", "approx")):
            # the observatory measures the CODED wire (encode → decode);
            # the baseline path ships raw rows, emits no optional metric
            # columns at all (no exactness certificate), and has no decode
            # to shadow — keeping it column-free preserves the PR 4
            # "baseline emits nothing" invariant
            raise ValueError(
                "numerics_watch/shadow_wire require a coded approach "
                f"(cyclic|maj_vote|approx), got {self.approach!r}"
            )
        if self.incident_watch not in ("off", "on"):
            raise ValueError(
                f"incident_watch must be off|on, got {self.incident_watch!r}"
            )
        if self.incident_thresholds:
            # unknown detector/threshold names surface at config time, not
            # mid-run (the registry is the contract); parse result is
            # rebuilt where it is consumed (obs/incidents.make_engine)
            from draco_tpu.obs.incidents import parse_thresholds

            parse_thresholds(self.incident_thresholds)
        if self.autopilot not in ("off", "on"):
            raise ValueError(
                f"autopilot must be off|on, got {self.autopilot!r}"
            )
        if self.autopilot == "on":
            if self.incident_watch != "on":
                raise ValueError(
                    "autopilot='on' requires incident_watch='on' — the "
                    "incident stream IS the sensing layer the policy "
                    "engine actuates on (control/autopilot.py)"
                )
            if not self.train_dir:
                raise ValueError(
                    "autopilot='on' needs a train_dir (the incident "
                    "stream and the control status block live there)"
                )
            if self.steps_per_call <= 1 and not (
                    self.network == "TransformerLM"
                    and self.token_gen == "device"):
                raise ValueError(
                    "autopilot='on' requires the chunked regime "
                    "(steps_per_call > 1): chunk boundaries are the "
                    "actuation points — remediations apply between "
                    "dispatched chunks, never inside one"
                )
            if self.approach not in ("cyclic", "approx"):
                raise ValueError(
                    "autopilot='on' supports the algebraic code families "
                    f"(cyclic|approx), got approach={self.approach!r} — "
                    "the redundancy dial swaps between exactly those two"
                )
        if self.autopilot_policy:
            # unknown policy keys surface at config time (DEFAULT_POLICY
            # is the contract); the parsed dict is rebuilt where it is
            # consumed (control.autopilot.make_autopilot)
            from draco_tpu.control.autopilot import parse_policy

            parse_policy(self.autopilot_policy)
        if self.step_guard not in ("off", "on"):
            raise ValueError(
                f"step_guard must be off|on, got {self.step_guard!r}"
            )
        if self.guard_residual_tol <= 0:
            raise ValueError(
                f"guard_residual_tol must be > 0, got "
                f"{self.guard_residual_tol}"
            )
        if self.prefetch_timeout_s < 0:
            raise ValueError(
                f"prefetch_timeout_s must be >= 0, got "
                f"{self.prefetch_timeout_s}"
            )
        if self.prefetch_restarts < 0:
            raise ValueError(
                f"prefetch_restarts must be >= 0, got "
                f"{self.prefetch_restarts}"
            )
        if self.keep_checkpoints < 0:
            raise ValueError(
                f"keep_checkpoints must be >= 0, got {self.keep_checkpoints}"
            )
        if self.checkpoint_step < -1:
            raise ValueError(
                "checkpoint_step must be >= -1 (-1 resumes from the newest "
                f"loadable checkpoint), got {self.checkpoint_step}"
            )
        if self.fault_spec:
            # parse errors surface here (config time), not mid-run; the
            # parsed plan itself is rebuilt (cached) where it is consumed
            from draco_tpu.resilience.faults import FaultPlan

            plan = FaultPlan.parse(self.fault_spec, self.seed,
                                   self.num_workers)
            if self.approach == "approx" \
                    and plan.of_kind("over_budget", "adversary"):
                # both kinds mark schedule rows as live adversaries, but
                # the approx family injects no attacks (no Byzantine
                # certificate) — the event would be silently inert while
                # still flipping the packed adversary-mask telemetry
                raise ValueError(
                    "fault kinds over_budget/adversary are not expressible "
                    "under approach=approx (the family injects no "
                    "adversaries); use straggle/nan_grad/host kinds, or "
                    "cyclic/maj_vote for Byzantine-budget faults"
                )
        if self.straggle_mode not in ("none", "drop"):
            raise ValueError(f"unknown straggle_mode: {self.straggle_mode}")
        if self.decode_granularity not in ("global", "layer"):
            raise ValueError(
                f"decode_granularity must be global|layer, got {self.decode_granularity}"
            )
        if self.decode_impl not in ("auto", "xla", "pallas"):
            raise ValueError(
                f"decode_impl must be auto|xla|pallas, got {self.decode_impl}"
            )
        if self.redundancy not in ("simulate", "shared"):
            raise ValueError(f"redundancy must be simulate|shared, got {self.redundancy}")
        if self.adversary_count is not None and self.adversary_count > self.worker_fail:
            raise ValueError(
                "adversary_count cannot exceed worker_fail (the code is only "
                f"built to tolerate worker_fail={self.worker_fail})"
            )
        e = self.straggle_count if self.straggle_mode == "drop" else 0
        if e > 0:
            s, t, n = self.worker_fail, self.num_adversaries, self.num_workers
            if self.approach == "cyclic":
                # Erasures cost one redundancy unit, unknown errors two. The
                # decoder covers erasure-only (t=0, e <= 2s) and the joint
                # regime (t + e <= s), where the locator treats missing rows
                # as one error each. Under topology='tree' the budget is the
                # PER-GROUP one (worst case every straggler and adversary
                # lands in a single leaf group — the schedules are
                # independent of the group partition).
                s_eff = self.tree_group_fail if self.topology == "tree" \
                    else s
                if not ((t == 0 and e <= 2 * s_eff) or (t + e <= s_eff)):
                    label = ("per-group (tree) " if self.topology == "tree"
                             else "")
                    raise ValueError(
                        f"cyclic {label}straggler budget exceeded: need "
                        f"adversary_count + straggle_count <= s "
                        f"({t}+{e} <= {s_eff}), or adversary_count == 0 "
                        f"with straggle_count <= 2*s ({e} <= {2 * s_eff})"
                    )
            if self.approach == "approx":
                import math

                budget = math.ceil(self.straggler_alpha * n)
                if e > budget:
                    raise ValueError(
                        f"approx straggler budget exceeded: straggle_count "
                        f"{e} > ceil(straggler_alpha * n) = {budget} — raise "
                        f"--straggler-alpha (and code_redundancy with it) or "
                        f"drop fewer workers"
                    )
            if self.approach == "maj_vote":
                if e >= self.group_size:
                    raise ValueError(
                        f"straggle_count {e} >= group_size {self.group_size} can "
                        "silence an entire repetition group"
                    )
                # Worst case all e stragglers AND all t adversaries land in one
                # group (the schedules are independent): the vote among the
                # group_size - e present members needs an honest majority,
                # i.e. group_size - e > 2t — the joint budget, mirroring the
                # cyclic t + e <= s check above.
                if t > 0 and self.group_size - e <= 2 * t:
                    raise ValueError(
                        f"maj_vote joint budget exceeded: group_size - "
                        f"straggle_count must exceed 2*adversaries "
                        f"({self.group_size} - {e} <= {2 * t}); an unlucky "
                        "group could be voted over by adversarial rows"
                    )
            if self.approach == "baseline":
                if e >= n:
                    raise ValueError("straggle_count must leave at least one worker")
                if (self.mode in ("krum", "multi_krum", "bulyan")
                        and n - e < s + 3):
                    raise ValueError(
                        f"{self.mode} needs num_workers - straggle_count >= "
                        f"worker_fail + 3 ({n} - {e} < {s} + 3)"
                    )
                if (self.mode in ("coord_median", "trimmed_mean", "bulyan")
                        and n - e <= 2 * s):
                    # the median-based rules need an honest majority among
                    # the rows that actually arrive: with p <= 2s present
                    # rows, s Byzantine rows control the per-coordinate
                    # median (and hence the trim fill) outright
                    raise ValueError(
                        f"{self.mode} needs num_workers - straggle_count > "
                        f"2 * worker_fail ({n} - {e} <= {2 * s})"
                    )
        if self.network == "TransformerLM":
            if self.approach == "maj_vote":
                raise ValueError(
                    "approach=maj_vote is not supported for TransformerLM: the "
                    "vote's bitwise-equality contract is specified over "
                    "replicated CNN lanes (use baseline or cyclic; "
                    "draco_tpu/parallel/sp_step.py)"
                )
            if self.model_dim % self.model_heads != 0:
                raise ValueError(
                    f"model_dim {self.model_dim} not divisible by "
                    f"model_heads {self.model_heads}"
                )
            if (self.model_dim // self.model_heads) % 2 != 0:
                raise ValueError(
                    "head dim must be even for the rotary embedding "
                    f"(model_dim/model_heads = {self.model_dim // self.model_heads})"
                )
            if self.seq_len % max(self.seq_shards, 1) != 0:
                raise ValueError(
                    f"seq_len {self.seq_len} not divisible by seq_shards {self.seq_shards}"
                )
            if self.sp_attn not in ("ring", "a2a"):
                raise ValueError(f"sp_attn must be ring|a2a, got {self.sp_attn}")
            if self.attn_impl not in ("dense", "flash"):
                raise ValueError(
                    f"attn_impl must be dense|flash, got {self.attn_impl}"
                )
            # attn_impl=flash composes with BOTH sp modes: a2a runs the
            # kernel on each device's full-sequence head group after the
            # scatter; ring runs it per visiting K/V block with an lse merge
            # (parallel/ring_attention.ring_flash_attention)
            if self.attn_impl == "flash" and (
                self.tensor_shards > 1 or self.expert_shards > 1
                or self.moe_experts > 0
            ):
                raise ValueError(
                    "attn_impl=flash runs on the shard_map paths (sp/pp): "
                    "the GSPMD paths (tensor_shards/expert_shards/moe) "
                    "cannot partition an opaque Pallas call over the mesh"
                )
            # pp_microbatches alone activates the pipeline path (cli.py),
            # so it counts as the pp axis being in use
            pp_active = self.pipeline_shards > 1 or self.pp_microbatches > 0
            if (
                sum(int(x > 1) for x in
                    (self.tensor_shards, self.seq_shards, self.expert_shards))
                + int(pp_active)
                > 1
            ):
                raise ValueError(
                    "tensor_shards / seq_shards / expert_shards / "
                    "pipeline_shards are separate paths (tp_step / sp_step / "
                    "ep_step / pp_step); combining model-parallel axes is "
                    "not implemented"
                )
            if self.expert_shards > 1:
                if self.moe_experts <= 0:
                    raise ValueError("expert_shards > 1 needs moe_experts > 0")
                if self.moe_experts % self.expert_shards:
                    raise ValueError(
                        f"expert_shards={self.expert_shards} must divide "
                        f"moe_experts {self.moe_experts}"
                    )
            if self.moe_experts < 0:
                raise ValueError("moe_experts must be >= 0")
            if self.moe_experts > 0 and self.seq_shards > 1:
                # MoeMlp computes capacity and arrival-order drops from its
                # LOCAL token count; under sp sharding that breaks the
                # documented sp layout-invariance (global routing is not
                # implemented)
                raise ValueError(
                    "moe_experts > 0 with seq_shards > 1 is not implemented: "
                    "per-shard MoE routing/capacity would break sp "
                    "layout-invariance"
                )
            if self.tensor_shards > 1:
                if self.moe_experts > 0:
                    raise ValueError(
                        "tensor_shards with moe_experts is not implemented "
                        "(the tp partition rules cover the dense MLP only)"
                    )
                if (
                    self.model_dim % self.tensor_shards
                    or self.model_heads % self.tensor_shards
                ):
                    raise ValueError(
                        f"tensor_shards={self.tensor_shards} must divide "
                        f"model_dim {self.model_dim} and model_heads "
                        f"{self.model_heads}"
                    )
            if (
                self.sp_attn == "a2a"
                and self.seq_shards > 1
                and self.model_heads % self.seq_shards != 0
            ):
                raise ValueError(
                    f"sp_attn=a2a needs model_heads % seq_shards == 0 "
                    f"({self.model_heads} % {self.seq_shards})"
                )
            if self.pp_microbatches < 0 or self.pipeline_shards < 1:
                raise ValueError(
                    "pipeline_shards must be >= 1 and pp_microbatches >= 0"
                )
            if pp_active:
                if self.moe_experts > 0:
                    raise ValueError(
                        "the pipeline path with moe_experts is not implemented "
                        "(pp_step's scanned block stack covers the dense "
                        "MLP only)"
                    )
                if self.model_layers % max(self.pipeline_shards, 1):
                    raise ValueError(
                        f"pipeline_shards={self.pipeline_shards} must divide "
                        f"model_layers {self.model_layers}"
                    )
                mb = self.pp_microbatches or self.pipeline_shards
                if self.batch_size % mb:
                    raise ValueError(
                        f"pipeline microbatch count {mb} must divide "
                        f"batch_size {self.batch_size}"
                    )
            if self.seq_len < 2 or self.vocab < 2:
                raise ValueError("TransformerLM needs seq_len >= 2 and vocab >= 2")
        elif self.seq_shards > 1:
            raise ValueError("seq_shards > 1 requires network=TransformerLM")
        elif self.tensor_shards > 1:
            raise ValueError("tensor_shards > 1 requires network=TransformerLM")
        elif self.expert_shards > 1 or self.moe_experts > 0:
            raise ValueError(
                "moe_experts / expert_shards require network=TransformerLM"
            )
        elif self.pipeline_shards > 1:
            raise ValueError("pipeline_shards > 1 requires network=TransformerLM")
        return self
