"""Command-line entry point — flag parity with the reference's
``mpirun -n P+1 python distributed_nn.py`` (reference: src/distributed_nn.py:23-77),
minus the MPI: one process drives the whole mesh (or one per host under
multi-host jax.distributed).

Usage examples:
  python -m draco_tpu.cli --approach cyclic --network LeNet --dataset MNIST \\
      --num-workers 8 --worker-fail 1 --err-mode rev_grad --max-steps 500
  python -m draco_tpu.cli --approach baseline --mode geometric_median ...
"""

from __future__ import annotations

import argparse

from draco_tpu.config import AGG_MODES, SEED, TrainConfig


def add_fit_args(parser: argparse.ArgumentParser) -> argparse.ArgumentParser:
    """Reference: add_fit_args, distributed_nn.py:23-77."""
    p = parser
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--test-batch-size", type=int, default=1000)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--optimizer", type=str, default="sgd",
                   choices=["sgd", "adam", "adamw"])
    p.add_argument("--weight-decay", type=float, default=0.01,
                   help="adamw's decoupled weight decay (sgd/adam ignore it)")
    p.add_argument("--lr-schedule", type=str, default="constant",
                   choices=["constant", "cosine"],
                   help="cosine: linear warmup then cosine decay to 10%% "
                        "of --lr over --max-steps")
    p.add_argument("--warmup-steps", type=int, default=0)
    p.add_argument("--clip-norm", type=float, default=0.0,
                   help=">0: clip the decoded/aggregated gradient by global "
                        "norm before the optimizer (post-aggregation, so it "
                        "never changes what the Byzantine filter sees)")
    p.add_argument("--max-steps", type=int, default=10000)
    p.add_argument("--network", type=str, default="LeNet")
    p.add_argument("--dataset", type=str, default="MNIST")
    p.add_argument("--data-dir", type=str, default="./data")
    p.add_argument("--approach", type=str, default="baseline",
                   choices=["baseline", "maj_vote", "cyclic", "approx"])
    p.add_argument("--mode", type=str, default="normal",
                   choices=list(AGG_MODES),
                   help="aggregation for --approach baseline (first three "
                        "mirror the reference; the rest are beyond-reference "
                        "robust baselines)")
    p.add_argument("--num-workers", type=int, default=8,
                   help="logical workers n (the reference's mpirun -n minus the PS)")
    p.add_argument("--group-size", type=int, default=3,
                   help="repetition redundancy r for maj_vote")
    p.add_argument("--vote-check", type=str, default="fingerprint",
                   choices=["fingerprint", "exact"],
                   help="maj_vote row-equality check: salted O(r*d) "
                        "fingerprints vs collision-free O(r^2*d) exact "
                        "bit-equality (for mutually-untrusting deployments)")
    p.add_argument("--worker-fail", type=int, default=0, help="s Byzantine workers")
    # approximate code family (--approach approx; coding/approx.py, ISSUE 8)
    p.add_argument("--code-redundancy", type=float, default=1.5,
                   help="approx family: computational redundancy r in "
                        "[1, n] — each worker computes ~r batches (exact "
                        "codes pay r = 2s+1); decode error under drops is "
                        "bounded by the optimal-decoding least squares and "
                        "measured per step (decode_residual vs "
                        "decode_residual_bound metric columns)")
    p.add_argument("--straggler-alpha", type=float, default=0.25,
                   help="approx family design point: the decode is "
                        "dimensioned for up to ceil(alpha*n) absent workers "
                        "per step (--straggle-count is validated against it)")
    p.add_argument("--assignment-scheme", type=str, default="pairwise",
                   choices=["pairwise", "clustered"],
                   help="approx batch-to-worker assignment: pair-wise "
                        "balanced cyclic windows (any r) or clustered "
                        "fractional repetition (integer r dividing n; any "
                        "one survivor per cluster keeps the decode exact)")
    p.add_argument("--err-mode", type=str, default="rev_grad",
                   choices=["rev_grad", "constant", "random", "alie", "ipm"],
                   help="reference modes + colluding attacks on approximate "
                        "robust aggregation (alie: Baruch'19, ipm: Xie'20)")
    p.add_argument("--adversarial", type=float, default=-100.0,
                   help="attack magnitude (reference hardcoded -100)")
    p.add_argument("--adversary-count", type=int, default=None,
                   help="live adversaries per step (default: worker-fail); set "
                        "lower to leave decode budget for stragglers")
    p.add_argument("--straggle-mode", type=str, default="none",
                   choices=["none", "drop"],
                   help="drop: straggle-count workers miss each step's "
                        "deadline and are decoded around as erasures")
    p.add_argument("--straggle-count", type=int, default=0)
    p.add_argument("--redundancy", type=str, default=None,
                   choices=["simulate", "shared"],
                   help="simulate: r-times redundant compute like the reference; "
                        "shared: algebraically identical compute-once fast path "
                        "(default: simulate, except approach=approx which only "
                        "has the shared path)")
    p.add_argument("--decode-granularity", type=str, default="global",
                   choices=["global", "layer"],
                   help="cyclic decode: one locator on the flat gradient, or "
                        "one per parameter tensor like the reference "
                        "(cyclic_master.py:125-129)")
    p.add_argument("--decode-impl", type=str, default="auto",
                   choices=["auto", "xla", "pallas"],
                   help="coded-decode lowering (ops/decode_kernels.py): "
                        "auto = fused Pallas kernels on TPU backends / "
                        "historical XLA path elsewhere; xla pins the "
                        "historical path; pallas selects the fused kernels "
                        "(their reference XLA lowering off-TPU)")
    p.add_argument("--eval-freq", type=int, default=50)
    p.add_argument("--train-dir", type=str, default="./train_out/")
    p.add_argument("--job-name", type=str, default="",
                   help="operator-facing job label stamped into "
                        "status.json (schema 5) — the fleet observatory "
                        "(tools/fleet_report.py) labels runs by it")
    p.add_argument("--checkpoint-step", type=int, default=0)
    p.add_argument("--compress-ckpt", action="store_true",
                   help="write compressed .dcg checkpoints (the reference's "
                        "--compress-grad, applied where bytes still cross a "
                        "slow link in the SPMD design)")
    p.add_argument("--seed", type=int, default=SEED)
    p.add_argument("--log-every", type=int, default=10)
    # long-context / sequence parallelism (TPU-native addition; no reference
    # counterpart — the reference zoo is CNN-only, SURVEY.md §5.7)
    p.add_argument("--seq-shards", type=int, default=1,
                   help="sp mesh-axis size for network=TransformerLM")
    p.add_argument("--sp-attn", type=str, default="ring",
                   choices=["ring", "a2a"],
                   help="sequence-parallel attention: ring (ppermute K/V "
                        "blocks) or a2a (Ulysses head-scatter all_to_all)")
    p.add_argument("--attn-impl", type=str, default="dense",
                   choices=["dense", "flash"],
                   help="single-shard attention: dense (T,T) scores or the "
                        "Pallas blockwise flash kernel (long context on one "
                        "chip; ops/flash_attention.py)")
    p.add_argument("--tensor-shards", type=int, default=1,
                   help="tp mesh-axis size (Megatron GSPMD path, tp_step.py)")
    p.add_argument("--moe-experts", type=int, default=0,
                   help="Switch-MoE experts per block (0 = dense MLP)")
    p.add_argument("--expert-shards", type=int, default=1,
                   help="ep mesh-axis size sharding the expert stacks")
    p.add_argument("--pipeline-shards", type=int, default=1,
                   help="pp mesh-axis size (GPipe schedule, pp_step.py)")
    p.add_argument("--pp-microbatches", type=int, default=0,
                   help="microbatches per pipeline step (0 = pipeline-shards)")
    p.add_argument("--seq-len", type=int, default=256)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--model-dim", type=int, default=128)
    p.add_argument("--model-heads", type=int, default=4)
    p.add_argument("--model-layers", type=int, default=2)
    p.add_argument("--cpu-mesh", type=int, default=0, metavar="N",
                   help="force an N-device virtual CPU mesh (testing without TPUs)")
    p.add_argument("--steps-per-call", type=int, default=1,
                   help="K training steps fused into one device program "
                        "(lax.scan) — the CNN Trainer and every "
                        "TransformerLM route (sp/tp/ep/pp); hides per-step "
                        "host dispatch/RTT. Eval/checkpoint snap to chunk "
                        "boundaries. Keep 1 for conv nets on CPU (XLA:CPU "
                        "serializes conv thunks in scan bodies, PERF.md §4); "
                        "raise on accelerators and for matmul-dominated "
                        "models (TransformerLM/FC) everywhere")
    p.add_argument("--token-gen", type=str, default="host",
                   choices=["host", "device"],
                   help="TransformerLM token stream: host-generated numpy "
                        "batches, or regenerated in-graph from the scalar "
                        "(seed, step) so the chunked loop uploads K scalars "
                        "per dispatch (parallel/token_loop.py)")
    p.add_argument("--compute-dtype", type=str, default="float32",
                   choices=["float32", "bfloat16"],
                   help="forward/backward dtype; bfloat16 runs the MXU at "
                        "full rate (params/BN stats/logits stay float32)")
    p.add_argument("--remat", action="store_true",
                   help="rematerialise activations in backward (jax.checkpoint)")
    p.add_argument("--profile-dir", type=str, default="",
                   help="capture a jax.profiler device trace of a few steps "
                        "into this directory (SURVEY.md §5.1) — every "
                        "route: the coded-DP trainer and all five "
                        "TransformerLM token routes. With --steps-per-call "
                        "K > 1 the capture window snaps to whole chunks "
                        "(the chunks containing the profiled steps), since "
                        "a chunk is one indivisible device program")
    p.add_argument("--trace-dir", type=str, default="",
                   help="write a Chrome-trace-event trace.json of the HOST "
                        "phases (gather/upload/dispatch/sync/flush/eval/"
                        "ckpt + prefetcher lanes) into this directory — "
                        "open in Perfetto; complements --profile-dir's "
                        "device trace (draco_tpu/obs)")
    from draco_tpu.obs.compile_watch import GUARD_MODES

    p.add_argument("--compile-guard", type=str, default="warn",
                   choices=list(GUARD_MODES),
                   help="steady-state recompilation guard "
                        "(obs/compile_watch.py): every XLA executable build "
                        "is recorded in compiles.jsonl + the trace's "
                        "compile lane; after --compile-warmup builds per "
                        "program a further build warns (default) or raises "
                        "— a mid-run retrace re-pays the compile the "
                        "scan-chunked loops exist to amortize (PERF.md §8)")
    p.add_argument("--numerics-watch", type=str, default="off",
                   choices=["off", "on"],
                   help="numerics observatory (obs/numerics.py, ISSUE 10): "
                        "per-step dynamic-range columns (absmax/rms/"
                        "underflow-overflow fractions at the bf16 and "
                        "int8-per-block thresholds/exponent histogram) for "
                        "the pre-encode gradients, the wire codewords, and "
                        "the decoded aggregate — riding the (K, m) metric "
                        "block at zero extra device fetches (coded "
                        "approaches only)")
    p.add_argument("--wire-dtype", type=str, default="f32",
                   choices=["f32", "bf16", "int8"],
                   help="the REAL worker→aggregator wire dtype (ISSUE 15): "
                        "f32 keeps today's wire bit-for-bit; bf16/int8 "
                        "round the codewords into real narrow buffers "
                        "(int8 with per-block scales over --shadow-block "
                        "elements; --shadow-round stochastic = shared-draw "
                        "stochastic rounding) that cross the sharding "
                        "boundary narrow and widen to f32 only inside the "
                        "decode — 2–4× wire bytes/HBM (PERF.md §17). The "
                        "cyclic decode runs the quantization-aware flag "
                        "threshold + Tikhonov-regularized locator; coded "
                        "approaches only, exclusive with --shadow-wire")
    p.add_argument("--wire-segments", type=int, default=1,
                   help="streaming segmented wire (ISSUE 16): split the d "
                        "dimension of the coded wire into this many "
                        "segments — workers emit per-segment codeword "
                        "buffers and the aggregator decodes each segment "
                        "as it arrives (per-segment syndromes / partial-"
                        "recovery tails, health folded to one per-step "
                        "verdict). 1 keeps today's single-message wire "
                        "bit-for-bit; cuts align to the segment quantum "
                        "(TILE_D, else --shadow-block) so narrow buffers "
                        "are segment-invariant. Coded approaches only")
    p.add_argument("--topology", type=str, default="flat",
                   choices=["flat", "tree"],
                   help="aggregation topology (ISSUE 17, CodedReduce "
                        "arXiv:1902.01981): flat keeps the star — all n "
                        "codewords decode at one logical point; tree "
                        "partitions the worker axis into n/g leaf groups "
                        "of constant fan-in g (--tree-fanout), each "
                        "running the ONE shared small code at the per-"
                        "group budget s_g = min(s, (g-1)//4), decoded "
                        "partials combining level-structured — per-node "
                        "decode cost and ingest bytes are O(g·d), "
                        "independent of n. Cyclic/approx families, "
                        "shared redundancy, global decode granularity")
    p.add_argument("--tree-fanout", type=int, default=4,
                   help="leaf-group fan-in g under --topology tree: must "
                        "divide num-workers with at least 2 groups; the "
                        "per-group Byzantine budget is min(worker-fail, "
                        "(g-1)//4)")
    p.add_argument("--tree-levels", type=int, default=0,
                   help="tree depth L under --topology tree (0 = auto: "
                        "1 + ceil(log_g(n/g))); interior levels combine "
                        "decoded partials with fan-in ≤ g")
    p.add_argument("--shadow-wire", type=str, default="off",
                   choices=["off", "bf16", "int8"],
                   help="shadow-quantized coded wire: round the codewords "
                        "to this dtype in-graph, decode the shadow copy "
                        "alongside the f32 path (which alone updates "
                        "params), and emit shadow_err/shadow_residual/"
                        "shadow_flag_agree + shadow detection columns — "
                        "the ROADMAP item 4 measurement harness "
                        "(tools/wire_study.py drives the committed matrix)")
    p.add_argument("--shadow-round", type=str, default="nearest",
                   choices=["nearest", "stochastic"],
                   help="shadow quantizer rounding: deterministic nearest "
                        "or per-step seeded stochastic rounding (noise "
                        "shared across wire rows, so identical rows stay "
                        "identical)")
    p.add_argument("--shadow-block", type=int, default=256,
                   help="int8 shadow per-block scale granularity "
                        "(elements per f32 scale along the wire row)")
    p.add_argument("--incident-watch", type=str, default="off",
                   choices=["off", "on"],
                   help="incident engine (obs/incidents.py, ISSUE 13): "
                        "fold the telemetry column families + heartbeat "
                        "beats into typed, attributed run-health "
                        "incidents (throughput/residual-drift/trust-"
                        "collapse/guard-burn/numerics/compile-storm/"
                        "prefetch-starvation) with onset/offset "
                        "hysteresis — streamed to train_dir/"
                        "incidents.jsonl + the status.json incidents "
                        "block; host-side only, bitwise-transparent "
                        "(tools/incident_report.py replays it jax-free)")
    p.add_argument("--incident-thresholds", type=str, default="",
                   help="per-detector threshold overrides, comma-"
                        "separated '<detector>.<key>=<float>' (e.g. "
                        "'trust.floor=0.4'); keys validated against the "
                        "declarative registry (PERF.md §15 table)")
    p.add_argument("--autopilot", type=str, default="off",
                   choices=["off", "on"],
                   help="adaptive coding autopilot (draco_tpu/control): "
                        "consume the incident stream at chunk boundaries "
                        "and emit remediations — quarantine trust-"
                        "collapsed workers, dial cyclic redundancy down "
                        "to approx under sustained straggle/starvation "
                        "(and back up on clean evidence), drop the "
                        "shadow dtype on numerics_drift; warm cached "
                        "program swaps, every decision an attributed "
                        "remediation event + control status block. Needs "
                        "--incident-watch on, a --train-dir and "
                        "--steps-per-call > 1")
    p.add_argument("--autopilot-policy", type=str, default="",
                   help="autopilot policy overrides, comma-separated "
                        "'<key>=<float>' (e.g. 'r_low=1.2,"
                        "clean_boundaries=3'); keys validated against "
                        "control.autopilot.DEFAULT_POLICY (PERF.md §16)")
    p.add_argument("--compile-warmup", type=int, default=1,
                   help="XLA builds allowed per registered program (per "
                        "chunk shape) before the compile guard treats a "
                        "build as a steady-state recompilation")
    # resilience layer (draco_tpu/resilience; ISSUE 6)
    p.add_argument("--step-guard", type=str, default="off",
                   choices=["off", "on"],
                   help="in-graph step guard (resilience/guards.py): fold "
                        "decode-health signals + a global-finite check and "
                        "SKIP untrusted optimizer updates via branch-free "
                        "carry passthrough; emits guard_trips/"
                        "skipped_steps metric columns at zero extra device "
                        "fetches. Bitwise-transparent on clean steps")
    p.add_argument("--guard-residual-tol", type=float, default=1e-3,
                   help="decode_residual above this is a guard trip "
                        "(clean decodes sit at f32 solve noise ~1e-6)")
    p.add_argument("--fault-spec", type=str, default="",
                   help="deterministic fault-injection plan "
                        "(resilience/faults.py): comma-separated "
                        "'kind@step[:w<worker>][:d<seconds>]' events — "
                        "nan_grad/inf_grad/over_budget in-graph, "
                        "prefetch_crash/prefetch_hang/sigterm on the host; "
                        "tools/chaos_run.py drives the full matrix")
    p.add_argument("--prefetch-timeout", type=float, default=300.0,
                   dest="prefetch_timeout_s", metavar="SECONDS",
                   help="bound on a token-prefetch worker-thread queue "
                        "wait (0 = wait forever): a dead/hung worker "
                        "raises the named PrefetchStallError instead of "
                        "wedging the main loop (the CNN prefetchers' "
                        "native gather surfaces failures synchronously)")
    p.add_argument("--prefetch-restarts", type=int, default=2,
                   help="bounded prefetcher supervision: on a worker "
                        "exception/stall, abandon + rebuild the prefetcher "
                        "with exponential backoff up to N times before the "
                        "error propagates (0 disables)")
    p.add_argument("--keep-checkpoints", type=int, default=0, metavar="N",
                   help="retain-last-N checkpoint GC after every save (0 = "
                        "keep all, the historical behavior); the newest "
                        "checkpoint always survives")
    return p


def maybe_force_cpu_mesh(args: argparse.Namespace) -> None:
    """Tool bootstrap: enable the persistent XLA compile cache, then apply
    --cpu-mesh N (an N-device virtual CPU mesh instead of accelerators).
    Must run before any jax computation; safe to call twice. Every tool and
    bench.py routes through here so cache policy lives in one place.

    The cache is skipped when an explicit CPU mode is requested
    (--cpu-mesh / --cpu-interpret: CI smokes, where cache churn is waste)
    or when JAX_PLATFORMS=cpu is set (enable_compile_cache refuses there:
    cache-built XLA:CPU executables corrupt donated carries, PERF.md §9).
    It is NOT gated on the resolved backend — probing that here would
    initialize jax in-process, the exact ~25-minute wedge bench.py's
    subprocess probes exist to avoid — so a flagless run that silently
    FALLS BACK to CPU still caches XLA:CPU results and is exposed to the
    §9 donated-carry corruption; prefer an explicit --cpu-mesh (or
    JAX_PLATFORMS=cpu) whenever CPU execution is the intent. The
    microarch-fingerprint cache scoping separately guards against foreign
    feature-pinned CPU AOT reloads (the SIGILL hazard)."""
    if not (getattr(args, "cpu_mesh", 0) or getattr(args, "cpu_interpret", False)):
        from draco_tpu.runtime import enable_compile_cache

        enable_compile_cache()
    if getattr(args, "cpu_mesh", 0):
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.cpu_mesh}"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")


def config_from_args(args: argparse.Namespace) -> TrainConfig:
    return TrainConfig(
        network=args.network,
        dataset=args.dataset,
        data_dir=args.data_dir,
        batch_size=args.batch_size,
        test_batch_size=args.test_batch_size,
        optimizer=args.optimizer,
        weight_decay=args.weight_decay,
        lr_schedule=args.lr_schedule,
        warmup_steps=args.warmup_steps,
        clip_norm=args.clip_norm,
        lr=args.lr,
        momentum=args.momentum,
        max_steps=args.max_steps,
        num_workers=args.num_workers,
        approach=args.approach,
        mode=args.mode,
        group_size=args.group_size,
        vote_check=args.vote_check,
        worker_fail=args.worker_fail,
        code_redundancy=args.code_redundancy,
        straggler_alpha=args.straggler_alpha,
        assignment_scheme=args.assignment_scheme,
        err_mode=args.err_mode,
        adversarial=args.adversarial,
        adversary_count=args.adversary_count,
        straggle_mode=args.straggle_mode,
        straggle_count=args.straggle_count,
        # approx only has the shared (compute-once) encode path; resolve the
        # unset flag to it there so `--approach approx` works bare, while an
        # explicit --redundancy simulate still errors loudly in validate()
        redundancy=args.redundancy if args.redundancy is not None
        else ("shared" if args.approach == "approx" else "simulate"),
        decode_granularity=args.decode_granularity,
        decode_impl=args.decode_impl,
        compute_dtype=args.compute_dtype,
        steps_per_call=args.steps_per_call,
        token_gen=args.token_gen,
        trace_dir=args.trace_dir,
        compile_guard=args.compile_guard,
        compile_warmup=args.compile_warmup,
        numerics_watch=args.numerics_watch,
        wire_dtype=args.wire_dtype,
        wire_segments=args.wire_segments,
        topology=args.topology,
        tree_fanout=args.tree_fanout,
        tree_levels=args.tree_levels,
        shadow_wire=args.shadow_wire,
        shadow_round=args.shadow_round,
        shadow_block=args.shadow_block,
        incident_watch=args.incident_watch,
        incident_thresholds=args.incident_thresholds,
        autopilot=args.autopilot,
        autopilot_policy=args.autopilot_policy,
        step_guard=args.step_guard,
        guard_residual_tol=args.guard_residual_tol,
        fault_spec=args.fault_spec,
        prefetch_timeout_s=args.prefetch_timeout_s,
        prefetch_restarts=args.prefetch_restarts,
        keep_checkpoints=args.keep_checkpoints,
        remat=args.remat,
        eval_freq=args.eval_freq,
        train_dir=args.train_dir,
        job_name=args.job_name,
        checkpoint_step=args.checkpoint_step,
        compress_ckpt=args.compress_ckpt,
        seed=args.seed,
        log_every=args.log_every,
        seq_shards=args.seq_shards,
        sp_attn=args.sp_attn,
        attn_impl=args.attn_impl,
        tensor_shards=args.tensor_shards,
        moe_experts=args.moe_experts,
        expert_shards=args.expert_shards,
        pipeline_shards=args.pipeline_shards,
        pp_microbatches=args.pp_microbatches,
        seq_len=args.seq_len,
        vocab=args.vocab,
        model_dim=args.model_dim,
        model_heads=args.model_heads,
        model_layers=args.model_layers,
    ).validate()


def main(argv=None):
    parser = add_fit_args(argparse.ArgumentParser(description="draco_tpu trainer"))
    parser.add_argument("--preset", type=str, default="",
                        help="named BASELINE.json config (draco_tpu.presets); "
                             "other flags still override max-steps/eval/etc.")
    args = parser.parse_args(argv)

    maybe_force_cpu_mesh(args)

    from draco_tpu.runtime import init_distributed
    from draco_tpu.training.trainer import Trainer

    init_distributed()
    if args.preset:
        from draco_tpu.presets import get_preset

        cfg = get_preset(
            args.preset, max_steps=args.max_steps, eval_freq=args.eval_freq,
            train_dir=args.train_dir, checkpoint_step=args.checkpoint_step,
            log_every=args.log_every, compute_dtype=args.compute_dtype,
            data_dir=args.data_dir, trace_dir=args.trace_dir,
        )
    else:
        cfg = config_from_args(args)
    profile_dir = args.profile_dir or None
    if cfg.network == "TransformerLM":
        # model-parallel paths compose with coded DP on 2-D (w × axis)
        # meshes; config.validate() guarantees at most one axis is active.
        # --profile-dir routes to every one of them (run_token_loop;
        # chunk-snapped under steps_per_call > 1)
        if cfg.tensor_shards > 1:
            from draco_tpu.parallel import make_mesh_wtp
            from draco_tpu.parallel.tp_step import train_tp

            _, last = train_tp(cfg, make_mesh_wtp(cfg.num_workers,
                                                  cfg.tensor_shards),
                               profile_dir=profile_dir)
        elif cfg.expert_shards > 1:
            from draco_tpu.parallel import make_mesh_wep
            from draco_tpu.parallel.ep_step import train_ep

            _, last = train_ep(cfg, make_mesh_wep(cfg.num_workers,
                                                  cfg.expert_shards),
                               profile_dir=profile_dir)
        elif cfg.pipeline_shards > 1 or cfg.pp_microbatches > 0:
            # pp_microbatches alone still selects the pipeline path: the
            # GPipe schedule runs at S=1 with M microbatches (validated
            # above), rather than silently dropping the flag
            from draco_tpu.parallel import make_mesh_wpp
            from draco_tpu.parallel.pp_step import train_pp

            _, last = train_pp(cfg, make_mesh_wpp(cfg.num_workers,
                                                  cfg.pipeline_shards),
                               profile_dir=profile_dir)
        else:
            # long-context default: (w × sp) mesh, ring/a2a attention
            from draco_tpu.parallel import make_mesh_2d
            from draco_tpu.parallel.sp_step import train_sp

            _, last = train_sp(cfg, make_mesh_2d(cfg.num_workers,
                                                 cfg.seq_shards),
                               profile_dir=profile_dir)
        return last
    trainer = Trainer(cfg)
    try:
        last = trainer.run(profile_dir=profile_dir)
    finally:
        # drains the buffered MetricWriter (tail safety) and writes the
        # final trace.json window
        trainer.close()
    return last


if __name__ == "__main__":
    main()
