"""Optimizers with the reference's "aggregated-gradient-as-argument" semantics.

The reference's SGDModified / AdamModified (src/optim/sgd_modified.py:53-89,
src/optim/adam_modified.py:32-92) are torch optimizers whose ``.step(grads,
mode)`` consumes the PS-aggregated numpy gradients instead of ``.grad``. In
jax that is simply an optax-style GradientTransformation applied to the
decoded/aggregated gradient pytree — but the *update rules* here mirror
torch's formulations exactly (they differ from optax defaults):

  torch SGD-momentum: buf ← μ·buf + g  (first step: buf = g);  p ← p − lr·buf
  torch Adam:         m ← β1 m + (1−β1) g;  v ← β2 v + (1−β2) g²
                      p ← p − lr·√(1−β2ᵗ)/(1−β1ᵗ) · m/(√v + ε)
                      (ε added *outside* the bias-corrected sqrt, like torch)
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class SGDState(NamedTuple):
    momentum_buf: optax.Params
    initialized: jnp.ndarray  # scalar bool — torch's first-step buf = g rule


def sgd_modified(
    lr: float, momentum: float = 0.0, dampening: float = 0.0, weight_decay: float = 0.0,
    nesterov: bool = False,
) -> optax.GradientTransformation:
    """torch.optim.SGD update rule (reference: sgd_modified.py:70-89)."""

    def init(params):
        return SGDState(
            momentum_buf=jax.tree.map(jnp.zeros_like, params),
            initialized=jnp.zeros((), dtype=bool),
        )

    def update(grads, state, params=None):
        if weight_decay != 0.0:
            grads = jax.tree.map(lambda g, p: g + weight_decay * p, grads, params)
        if momentum != 0.0:
            def upd_buf(buf, g):
                # first step: buf = g; after: buf = μ·buf + (1-dampening)·g
                later = momentum * buf + (1.0 - dampening) * g
                return jnp.where(state.initialized, later, g)

            buf = jax.tree.map(upd_buf, state.momentum_buf, grads)
            if nesterov:
                d_p = jax.tree.map(lambda g, b: g + momentum * b, grads, buf)
            else:
                d_p = buf
            new_state = SGDState(momentum_buf=buf, initialized=jnp.ones((), dtype=bool))
        else:
            d_p = grads
            new_state = state
        updates = jax.tree.map(lambda d: -lr * d, d_p)
        return updates, new_state

    return optax.GradientTransformation(init, update)


class AdamState(NamedTuple):
    count: jnp.ndarray
    exp_avg: optax.Params
    exp_avg_sq: optax.Params


def adam_modified(
    lr: float = 1e-3, b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """torch.optim.Adam update rule (reference: adam_modified.py:32-92)."""

    def init(params):
        return AdamState(
            count=jnp.zeros((), jnp.int32),
            exp_avg=jax.tree.map(jnp.zeros_like, params),
            exp_avg_sq=jax.tree.map(jnp.zeros_like, params),
        )

    def update(grads, state, params=None):
        if weight_decay != 0.0:
            grads = jax.tree.map(lambda g, p: g + weight_decay * p, grads, params)
        count = state.count + 1
        m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g, state.exp_avg, grads)
        v = jax.tree.map(lambda v_, g: b2 * v_ + (1 - b2) * g * g, state.exp_avg_sq, grads)
        t = count.astype(jnp.float32)
        bc1 = 1.0 - b1**t
        bc2 = 1.0 - b2**t
        step_size = lr * jnp.sqrt(bc2) / bc1
        updates = jax.tree.map(lambda m_, v_: -step_size * m_ / (jnp.sqrt(v_) + eps), m, v)
        return updates, AdamState(count=count, exp_avg=m, exp_avg_sq=v)

    return optax.GradientTransformation(init, update)


def adamw_modified(
    lr: float = 1e-3, b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
    weight_decay: float = 0.01,
) -> optax.GradientTransformation:
    """torch.optim.AdamW update rule (decoupled weight decay,
    Loshchilov & Hutter '19): p ← p·(1 − lr·λ), then the Adam step on the
    RAW gradient. Beyond-reference (the reference predates AdamW's
    dominance) but the LM paths' natural optimizer; same
    aggregated-gradient-as-argument contract as the parity rules above."""
    adam = adam_modified(lr=lr, b1=b1, b2=b2, eps=eps, weight_decay=0.0)

    def init(params):
        return adam.init(params)

    def update(grads, state, params=None):
        updates, new_state = adam.update(grads, state, params)
        if weight_decay != 0.0:
            updates = jax.tree.map(
                lambda u, p: u - lr * weight_decay * p, updates, params
            )
        return updates, new_state

    return optax.GradientTransformation(init, update)


def lr_schedule(name: str, lr: float, warmup_steps: int = 0,
                total_steps: int = 0):
    """step (0-based update count) -> learning-rate multiplier path.

    "constant": lr. "cosine": linear warmup over ``warmup_steps`` then a
    cosine decay to 10% of peak at ``total_steps`` (the standard LM recipe;
    beyond-reference — the reference trains at fixed lr)."""
    if name == "constant":
        return lambda t: lr
    if name == "cosine":
        # deliberately NOT optax.warmup_cosine_decay_schedule: its warmup
        # ramps from init_value at t=0, giving a wasted ~zero-lr first
        # update; this ramp hits (t+1)/warmup so step 0 already moves and
        # step warmup-1 is exactly peak. Numerics are pinned by
        # tests/test_models_optim_data.py::test_cosine_schedule_shape.
        floor = 0.1 * lr

        def sched(t):
            t = jnp.asarray(t, jnp.float32)
            warm = lr * (t + 1.0) / max(warmup_steps, 1)
            span = max(total_steps - warmup_steps, 1)
            frac = jnp.clip((t - warmup_steps) / span, 0.0, 1.0)
            cos = floor + (lr - floor) * 0.5 * (1.0 + jnp.cos(jnp.pi * frac))
            return jnp.where(t < warmup_steps, warm, cos)

        return sched
    raise ValueError(f"unknown lr schedule: {name}")


def build_optimizer(name: str, lr: float, momentum: float = 0.0,
                    weight_decay: float = 0.01, schedule: str = "constant",
                    warmup_steps: int = 0, total_steps: int = 0,
                    clip_norm: float = 0.0) -> optax.GradientTransformation:
    """The torch-parity rules bake ``-lr`` into their updates; under a
    schedule they run at lr=1 (their direction algebra — momentum buffers,
    bias correction, decoupled decay — is lr-independent) and
    ``optax.scale_by_schedule`` applies the time-varying rate, so every
    rule composes with every schedule.

    ``clip_norm`` > 0 clips the incoming gradient by global norm BEFORE the
    rule. In this framework the optimizer consumes the already
    decoded/aggregated gradient, so clipping is post-aggregation — it
    bounds step size without interacting with Byzantine filtering (a
    per-worker pre-aggregation clip would change what the vote/decode/
    median see and is deliberately not offered). The clip is applied as a
    STATELESS wrapper (not an optax.chain stage), and EVERY schedule —
    constant included (lr_schedule's degenerate branch) — goes through the
    same chain(rule, scale_by_schedule) composition, so the opt-state
    pytree structure is invariant across every knob: any checkpoint written
    by this version restores under any schedule family or clip setting.
    (Constant-schedule checkpoints written BEFORE this change carry the bare
    rule's state without the schedule-count leaf and need a fresh opt state
    — a one-time break, traded for structural invariance ever after.)"""
    if schedule != "constant" and total_steps <= 0:
        raise ValueError(
            f"schedule={schedule!r} needs total_steps > 0 (got "
            f"{total_steps}); without it the decay span collapses and the "
            f"whole run trains at the floor rate"
        )

    def base(rate: float) -> optax.GradientTransformation:
        if name == "sgd":
            return sgd_modified(lr=rate, momentum=momentum)
        if name == "adam":
            return adam_modified(lr=rate)
        if name == "adamw":
            return adamw_modified(lr=rate, weight_decay=weight_decay)
        raise ValueError(f"unknown optimizer: {name}")

    sched = lr_schedule(schedule, lr, warmup_steps, total_steps)
    core = optax.chain(base(1.0), optax.scale_by_schedule(sched))
    if clip_norm > 0.0:
        def clipped_update(grads, state, params=None):
            g_norm = optax.global_norm(grads)
            scale = jnp.minimum(1.0, clip_norm / jnp.maximum(g_norm, 1e-16))
            grads = jax.tree.map(lambda g: g * scale, grads)
            return core.update(grads, state, params)

        return optax.GradientTransformation(core.init, clipped_update)
    return core


def build_optimizer_from_cfg(cfg) -> optax.GradientTransformation:
    """One mapping from TrainConfig to the optimizer, shared by every
    training path (step.py and parallel/{pp,tp,sp}_step.py) so a new knob
    cannot be threaded into three of four topologies."""
    return build_optimizer(
        cfg.optimizer, cfg.lr, cfg.momentum,
        weight_decay=cfg.weight_decay, schedule=cfg.lr_schedule,
        warmup_steps=cfg.warmup_steps, total_steps=cfg.max_steps,
        clip_norm=cfg.clip_norm,
    )
