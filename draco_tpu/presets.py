"""The five benchmark configurations of BASELINE.json, as named presets.

These are the runs the reference pins down (BASELINE.md: run_pytorch.sh's
canonical cyclic config plus the paper's ResNet/VGG robustness grids); the
capability-parity checklist (SURVEY.md §7.5) requires each to run end-to-end.

  python -m draco_tpu.cli --preset cyclic-resnet18 --max-steps 2000
  python tools/run_baselines.py --smoke     # all five, short, any hardware
"""

from __future__ import annotations

import dataclasses

from draco_tpu.config import TrainConfig

PRESETS: dict[str, TrainConfig] = {
    # 1. LeNet/MNIST single-machine vanilla SGD (no coding, no adversary)
    "single-lenet": TrainConfig(
        network="LeNet", dataset="MNIST", approach="baseline", mode="normal",
        num_workers=1, worker_fail=0, batch_size=128, lr=0.01, momentum=0.9,
    ),
    # 2. ResNet-18/CIFAR-10, repetition code r=3, no adversary
    "rep-resnet18": TrainConfig(
        network="ResNet18", dataset="Cifar10", approach="maj_vote",
        group_size=3, num_workers=9, worker_fail=0, batch_size=32,
        lr=0.01, momentum=0.9,
    ),
    # 3. ResNet-18/CIFAR-10, cyclic code r=3 (s=1), reverse-gradient adversary
    "cyclic-resnet18": TrainConfig(
        network="ResNet18", dataset="Cifar10", approach="cyclic",
        num_workers=9, worker_fail=1, err_mode="rev_grad", batch_size=32,
        lr=0.01, momentum=0.9,
    ),
    # 4. VGG-11/CIFAR-10, cyclic code r=5 (s=2), constant attack (the
    # reference's "random" mode is a passthrough, model_ops/utils.py:20-21)
    "cyclic-vgg11": TrainConfig(
        network="VGG11", dataset="Cifar10", approach="cyclic",
        num_workers=9, worker_fail=2, err_mode="constant", batch_size=32,
        lr=0.01, momentum=0.9,
    ),
    # 5a/5b. robust-aggregation baselines under the same adversary schedule
    "geomedian-resnet18": TrainConfig(
        network="ResNet18", dataset="Cifar10", approach="baseline",
        mode="geometric_median", num_workers=9, worker_fail=1,
        err_mode="rev_grad", batch_size=32, lr=0.01, momentum=0.9,
    ),
    "krum-resnet18": TrainConfig(
        network="ResNet18", dataset="Cifar10", approach="baseline",
        mode="krum", num_workers=9, worker_fail=1, err_mode="rev_grad",
        batch_size=32, lr=0.01, momentum=0.9,
    ),
    # 6. beyond-reference (ISSUE 8): the straggler-dominated scenario —
    # ResNet-18/CIFAR-10 on the approximate code at r=1.5 (vs the exact
    # codes' r=3 above), dimensioned for up to ⌈0.25·9⌉ = 3 drops per step
    # with the residual-vs-bound certificate riding the metric block. No
    # live adversary: this family trades the Byzantine certificate for
    # redundancy near 1 (coding/approx.py).
    "approx-resnet18": TrainConfig(
        network="ResNet18", dataset="Cifar10", approach="approx",
        num_workers=9, worker_fail=0, redundancy="shared",
        code_redundancy=1.5, straggler_alpha=0.25,
        straggle_mode="drop", straggle_count=2, batch_size=32,
        lr=0.01, momentum=0.9,
    ),
}


def get_preset(name: str, **overrides) -> TrainConfig:
    if name not in PRESETS:
        raise ValueError(f"unknown preset {name!r} (have {sorted(PRESETS)})")
    return dataclasses.replace(PRESETS[name], **overrides).validate()
