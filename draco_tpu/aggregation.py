"""Robust gradient aggregation rules as pure (n, d) -> (d,) functions.

These replace the reference PS-side aggregation (src/master/baseline_master.py:
_avg_received_grads :267, _get_geo_median :271 via the hdmedians C extension,
_krum :278-296) with on-device jax implementations, so Draco's
"decode ≪ geometric median" comparison runs entirely on TPU.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def mean(grads: jnp.ndarray) -> jnp.ndarray:
    """Plain averaging (update_mode "normal")."""
    return jnp.mean(grads, axis=0)


def geometric_median(grads: jnp.ndarray, iters: int = 80, eps: float = 1e-8) -> jnp.ndarray:
    """Weiszfeld iteration for the geometric median of n rows.

    Replaces hdmedians.geomedian (baseline_master.py:274). Fixed iteration
    count keeps the op jittable; 80 iterations drives the relative change
    far below float32 resolution for the gradient scales involved.
    """

    def body(_, y):
        dist = jnp.linalg.norm(grads - y[None, :], axis=1)
        w = 1.0 / jnp.maximum(dist, eps)
        return (w @ grads) / jnp.sum(w)

    return jax.lax.fori_loop(0, iters, body, jnp.mean(grads, axis=0))


def krum(grads: jnp.ndarray, s: int) -> jnp.ndarray:
    """Krum (Blanchard et al.): select the row closest to its n-s-2 nearest
    neighbours. Mirrors baseline_master.py:278-296: score_i = sum of the
    n-s-2 smallest squared distances to the *other* rows; pick argmin.
    """
    n = grads.shape[0]
    if n < s + 3:
        raise ValueError(f"krum requires n >= s+3 (got n={n}, s={s})")
    k = n - s - 2
    # ||gi-gj||^2 via the Gram identity: one (n,d)@(d,n) MXU matmul instead of
    # an (n,n,d) broadcast intermediate
    gram = jnp.matmul(grads, grads.T, precision=jax.lax.Precision.HIGHEST)
    norms = jnp.diag(gram)
    sq = jnp.maximum(norms[:, None] + norms[None, :] - 2.0 * gram, 0.0)
    sq = sq + jnp.diag(jnp.full((n,), jnp.inf, dtype=grads.dtype))
    neighbor_sorted = jnp.sort(sq, axis=1)
    scores = jnp.sum(neighbor_sorted[:, :k], axis=1)
    return grads[jnp.argmin(scores)]


def aggregate(grads: jnp.ndarray, mode: str, s: int = 0, geomedian_iters: int = 80) -> jnp.ndarray:
    """Dispatch used by the baseline training step (mode parity with
    baseline_master.py:118-129)."""
    if mode == "normal":
        return mean(grads)
    if mode == "geometric_median":
        return geometric_median(grads, iters=geomedian_iters)
    if mode == "krum":
        return krum(grads, s)
    raise ValueError(f"unknown aggregation mode: {mode}")
