"""Robust gradient aggregation rules as pure (n, d) -> (d,) functions.

These replace the reference PS-side aggregation (src/master/baseline_master.py:
_avg_received_grads :267, _get_geo_median :271 via the hdmedians C extension,
_krum :278-296) with on-device jax implementations, so Draco's
"decode ≪ geometric median" comparison runs entirely on TPU.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

# Every rule takes an optional ``present`` mask ((n,) bool): False rows never
# arrived (stragglers — the reference PS would block forever on them,
# baseline_master.py:112-116) and are excluded from the statistic while
# keeping every shape static under jit.


def mean(grads: jnp.ndarray, present: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Plain averaging (update_mode "normal"), over present rows."""
    if present is None:
        return jnp.mean(grads, axis=0)
    w = present.astype(grads.dtype)
    return (w @ grads) / jnp.maximum(jnp.sum(w), 1.0)


def geometric_median(grads: jnp.ndarray, iters: int = 80, eps: float = 1e-8,
                     present: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Weiszfeld iteration for the geometric median of the present rows.

    Replaces hdmedians.geomedian (baseline_master.py:274). Fixed iteration
    count keeps the op jittable; 80 iterations drives the relative change
    far below float32 resolution for the gradient scales involved. Absent
    rows get weight 0 — the Weiszfeld weights absorb the mask exactly.
    """
    pw = None if present is None else present.astype(grads.dtype)

    def body(_, y):
        dist = jnp.linalg.norm(grads - y[None, :], axis=1)
        w = 1.0 / jnp.maximum(dist, eps)
        if pw is not None:
            w = w * pw
        return (w @ grads) / jnp.maximum(jnp.sum(w), 1e-30)

    return jax.lax.fori_loop(0, iters, body, mean(grads, present))


def krum(grads: jnp.ndarray, s: int,
         present: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Krum (Blanchard et al.): select the row closest to its n-s-2 nearest
    neighbours. Mirrors baseline_master.py:278-296: score_i = sum of the
    n-s-2 smallest squared distances to the *other* rows; pick argmin.

    With a present mask, absent rows are unselectable and distances to them
    rank last (k stays n-s-2 — conservative when rows are missing).
    """
    n = grads.shape[0]
    if n < s + 3:
        raise ValueError(f"krum requires n >= s+3 (got n={n}, s={s})")
    k = n - s - 2
    # ||gi-gj||^2 via the Gram identity: one (n,d)@(d,n) MXU matmul instead of
    # an (n,n,d) broadcast intermediate
    gram = jnp.matmul(grads, grads.T, precision=jax.lax.Precision.HIGHEST)
    norms = jnp.diag(gram)
    sq = jnp.maximum(norms[:, None] + norms[None, :] - 2.0 * gram, 0.0)
    # penalty for self/absent entries: must outrank every real distance but
    # stay bounded — n of them can land inside one row's k nearest slots
    # (straggle_count > s+1 is valid baseline config) and a finfo.max-scale
    # constant would overflow the score sum to inf for every row, degenerating
    # argmin to index 0
    big = 2.0 * jnp.max(sq) + 1.0
    sq = sq + jnp.diag(jnp.full((n,), 1.0, dtype=grads.dtype)) * big
    if present is not None:
        absent = ~present
        sq = sq + big * absent[None, :].astype(grads.dtype)
    neighbor_sorted = jnp.sort(sq, axis=1)
    scores = jnp.sum(neighbor_sorted[:, :k], axis=1)
    if present is not None:
        scores = jnp.where(present, scores, jnp.inf)
    return grads[jnp.argmin(scores)]


def aggregate(grads: jnp.ndarray, mode: str, s: int = 0, geomedian_iters: int = 80,
              present: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Dispatch used by the baseline training step (mode parity with
    baseline_master.py:118-129)."""
    if mode == "normal":
        return mean(grads, present=present)
    if mode == "geometric_median":
        return geometric_median(grads, iters=geomedian_iters, present=present)
    if mode == "krum":
        return krum(grads, s, present=present)
    raise ValueError(f"unknown aggregation mode: {mode}")
