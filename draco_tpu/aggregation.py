"""Robust gradient aggregation rules as pure (n, d) -> (d,) functions.

These replace the reference PS-side aggregation (src/master/baseline_master.py:
_avg_received_grads :267, _get_geo_median :271 via the hdmedians C extension,
_krum :278-296) with on-device jax implementations, so Draco's
"decode ≪ geometric median" comparison runs entirely on TPU.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from draco_tpu.config import AGG_MODES as MODES  # one source of truth

# Every rule takes an optional ``present`` mask ((n,) bool): False rows never
# arrived (stragglers — the reference PS would block forever on them,
# baseline_master.py:112-116) and are excluded from the statistic while
# keeping every shape static under jit.


def mean(grads: jnp.ndarray, present: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Plain averaging (update_mode "normal"), over present rows."""
    if present is None:
        return jnp.mean(grads, axis=0)
    w = present.astype(grads.dtype)
    return (w @ grads) / jnp.maximum(jnp.sum(w), 1.0)


def geometric_median(grads: jnp.ndarray, iters: int = 80, eps: float = 1e-8,
                     present: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Weiszfeld iteration for the geometric median of the present rows.

    Replaces hdmedians.geomedian (baseline_master.py:274). Fixed iteration
    count keeps the op jittable; 80 iterations drives the relative change
    far below float32 resolution for the gradient scales involved. Absent
    rows get weight 0 — the Weiszfeld weights absorb the mask exactly.
    """
    pw = None if present is None else present.astype(grads.dtype)

    def body(_, y):
        dist = jnp.linalg.norm(grads - y[None, :], axis=1)
        w = 1.0 / jnp.maximum(dist, eps)
        if pw is not None:
            w = w * pw
        return (w @ grads) / jnp.maximum(jnp.sum(w), 1e-30)

    return jax.lax.fori_loop(0, iters, body, mean(grads, present))


def krum(grads: jnp.ndarray, s: int,
         present: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Krum (Blanchard et al.): select the row closest to its n-s-2 nearest
    neighbours. Mirrors baseline_master.py:278-296: score_i = sum of the
    n-s-2 smallest squared distances to the *other* rows; pick argmin.

    With a present mask, absent rows are unselectable and distances to them
    rank last (k stays n-s-2 — conservative when rows are missing).
    """
    n = grads.shape[0]
    if n < s + 3:
        raise ValueError(f"krum requires n >= s+3 (got n={n}, s={s})")
    return grads[jnp.argmin(_krum_scores(grads, s, present))]


def _masked_median(grads: jnp.ndarray, present: jnp.ndarray) -> jnp.ndarray:
    """Per-coordinate median over present rows only, static shapes under
    jit: absent rows sort to +inf and the median index is computed from the
    (traced) present count."""
    x = jnp.where(present[:, None], grads, jnp.inf)
    x = jnp.sort(x, axis=0)
    np_ = jnp.sum(present).astype(jnp.int32)
    lo = jnp.maximum((np_ - 1) // 2, 0)
    hi = jnp.maximum(np_ // 2, 0)
    take = lambda i: jnp.take_along_axis(
        x, jnp.full((1, grads.shape[1]), i), axis=0
    )[0]
    return 0.5 * (take(lo) + take(hi))


def coordinate_median(grads: jnp.ndarray,
                      present: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Coordinate-wise median (Yin et al. 2018) — beyond the reference's
    aggregator set; tolerates < n/2 Byzantine rows per coordinate. With a
    present mask the median is taken over present rows only (absent rows
    carry no information and must not vote)."""
    if present is not None:
        return _masked_median(grads, present)
    return jnp.median(grads, axis=0)


def trimmed_mean(grads: jnp.ndarray, s: int,
                 present: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Coordinate-wise s-trimmed mean (Yin et al. 2018): drop the s largest
    and s smallest values per coordinate, average the rest. Requires
    n > 2s. With a present mask the trim runs over present rows ONLY —
    ranks are taken among present values (absent rows sort past the top and
    never vote) and the kept middle is ranks [s, n_present - s). Filling
    absent rows with a statistic and trimming all n would plant e fill
    copies inside the kept middle and bias the mean toward the fill
    (advisor r2); the e-shrunken middle keeps the estimator honest instead
    (guarantee needs n_present > 2s — the config straggler budget).
    """
    n = grads.shape[0]
    if n <= 2 * s:
        raise ValueError(f"trimmed_mean requires n > 2s (got n={n}, s={s})")
    if present is None:
        ordered = jnp.sort(grads, axis=0)
        kept = ordered[s:n - s] if s > 0 else ordered
        return jnp.mean(kept, axis=0)
    x = jnp.where(present[:, None], grads, jnp.inf)
    ranks = jnp.argsort(jnp.argsort(x, axis=0), axis=0)
    n_p = jnp.sum(present).astype(jnp.int32)
    hi = jnp.maximum(n_p - s, s + 1)  # keep >= 1 row even when n_p <= 2s
    w = (ranks >= s) & (ranks < hi) & present[:, None]
    # select by where, not by multiply: 0 * inf/NaN = NaN would let a
    # non-finite excluded row (overflowed or Byzantine) poison the sum
    kept = jnp.where(w, grads, 0.0)
    return jnp.sum(kept, axis=0) / jnp.maximum(
        jnp.sum(w.astype(grads.dtype), axis=0), 1.0)


def multi_krum(grads: jnp.ndarray, s: int, m: Optional[int] = None,
               present: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Multi-Krum (Blanchard et al.): average the m lowest-Krum-score rows
    (m = n_present - s - 2 by default) instead of returning a single row —
    lower variance than Krum at the same tolerance. The kept count is
    derived from the number of rows that actually arrived: with stragglers,
    keeping n - s - 2 rows could select every present row and degenerate to
    a contaminated plain mean.
    """
    n = grads.shape[0]
    if n < s + 3:
        raise ValueError(f"multi_krum requires n >= s+3 (got n={n}, s={s})")
    scores = _krum_scores(grads, s, present)
    # row rank among ascending scores (absent rows score +inf → rank last)
    rank = jnp.argsort(jnp.argsort(scores))
    if m is not None:
        keep = jnp.asarray(m, jnp.int32)
    elif present is None:
        keep = jnp.asarray(n - s - 2, jnp.int32)
    else:
        keep = jnp.maximum(
            jnp.sum(present).astype(jnp.int32) - s - 2, 1
        )
    w = rank < keep
    if present is not None:
        w = w & present
    # select by where, not by multiply (0 * inf/NaN = NaN — see trimmed_mean)
    kept = jnp.where(w[:, None], grads, 0.0)
    return jnp.sum(kept, axis=0) / jnp.maximum(
        jnp.sum(w.astype(grads.dtype)), 1.0)


def bulyan(grads: jnp.ndarray, s: int,
           present: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Bulyan (El Mhamdi et al. 2018): Multi-Krum-select θ rows, then a
    coordinate-wise β-centered average around the selection's coordinate
    median. Requires n >= 4s + 3 for the full guarantee; θ and β derive
    from the rows that actually *arrived* (θ = n_present - 2s) — deriving
    them from the static n would, under stragglers, select every present
    row and skip the Krum filtering entirely (the same degeneration
    multi_krum guards against). All selections are rank masks, so shapes
    stay static under jit with a traced present count.
    """
    n = grads.shape[0]
    if n <= 2 * s or n < s + 3:
        raise ValueError(f"bulyan requires n > 2s and n >= s+3 (n={n}, s={s})")
    if n < 4 * s + 3:
        # run anyway (useful as a robust heuristic) but say so: β clamps to
        # max(θ-2s, 1) and the rule degrades toward per-coordinate
        # nearest-to-median without the Byzantine guarantee (advisor r2).
        # Fires at trace time, so it lands once per jitted program, not per
        # step.
        import warnings

        warnings.warn(
            f"bulyan: n={n} < 4s+3={4 * s + 3}; the full Byzantine guarantee "
            f"does not hold and the rule degrades toward per-coordinate "
            f"nearest-to-median (beta clamps to 1)",
            stacklevel=2,
        )
    scores = _krum_scores(grads, s, present)
    rank = jnp.argsort(jnp.argsort(scores))
    if present is None:
        n_p = jnp.asarray(n, jnp.int32)
        pmask = jnp.ones((n,), bool)
    else:
        n_p = jnp.sum(present).astype(jnp.int32)
        pmask = present
    theta = jnp.maximum(n_p - 2 * s, 1)
    sel = (rank < theta) & pmask
    med = _masked_median(grads, sel)
    # per coordinate: average the β selected values closest to the median
    beta = jnp.maximum(theta - 2 * s, 1)
    dist = jnp.where(sel[:, None], jnp.abs(grads - med[None, :]), jnp.inf)
    cranks = jnp.argsort(jnp.argsort(dist, axis=0), axis=0)
    w = (cranks < beta) & sel[:, None]
    # select by where, not by multiply (0 * inf/NaN = NaN — see trimmed_mean)
    kept = jnp.where(w, grads, 0.0)
    return jnp.sum(kept, axis=0) / jnp.maximum(
        jnp.sum(w.astype(grads.dtype), axis=0), 1.0)


def _krum_scores(grads: jnp.ndarray, s: int,
                 present: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Krum scores (shared by krum / multi_krum / bulyan); absent rows score
    +inf and rank last as neighbours. Rows with non-finite entries (an
    overflowed/NaN Byzantine gradient) are likewise unselectable and rank
    last — inf distances would otherwise overflow every score and
    degenerate argmin to the attacker's row."""
    n = grads.shape[0]
    k = n - s - 2
    finite = jnp.all(jnp.isfinite(grads), axis=1)
    g_safe = jnp.where(finite[:, None], grads, 0.0)
    # ||gi-gj||^2 via the Gram identity: one (n,d)@(d,n) MXU matmul instead
    # of an (n,n,d) broadcast intermediate
    gram = jnp.matmul(g_safe, g_safe.T, precision=jax.lax.Precision.HIGHEST)
    norms = jnp.diag(gram)
    sq = jnp.maximum(norms[:, None] + norms[None, :] - 2.0 * gram, 0.0)
    # penalty for self/absent/non-finite entries: must outrank every real
    # distance but stay bounded — n of them can land inside one row's k
    # nearest slots (straggle_count > s+1 is valid baseline config) and a
    # finfo.max-scale constant would overflow the score sum to inf for
    # every row, degenerating argmin to index 0
    big = 2.0 * jnp.max(sq) + 1.0
    sq = sq + jnp.diag(jnp.full((n,), 1.0, dtype=grads.dtype)) * big
    sq = sq + big * (~finite)[None, :].astype(grads.dtype)
    if present is not None:
        sq = sq + big * (~present)[None, :].astype(grads.dtype)
    neighbor_sorted = jnp.sort(sq, axis=1)
    scores = jnp.sum(neighbor_sorted[:, :k], axis=1)
    scores = jnp.where(finite, scores, jnp.inf)
    if present is not None:
        scores = jnp.where(present, scores, jnp.inf)
    return scores


def aggregate(grads: jnp.ndarray, mode: str, s: int = 0, geomedian_iters: int = 80,
              present: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Dispatch used by the baseline training step. The first three modes
    mirror the reference (baseline_master.py:118-129); the rest are
    beyond-reference robust baselines under the same attack schedules."""
    if present is not None:
        # an absent row's values never arrived and must never matter — not
        # even as 0·x products (x could be NaN/inf from a simulated-straggler
        # lane that diverged); zero placeholders make every rule's masked
        # arithmetic finite
        grads = jnp.where(present[:, None], grads, 0.0)
    if mode == "normal":
        return mean(grads, present=present)
    if mode == "geometric_median":
        return geometric_median(grads, iters=geomedian_iters, present=present)
    if mode == "krum":
        return krum(grads, s, present=present)
    if mode == "coord_median":
        return coordinate_median(grads, present=present)
    if mode == "trimmed_mean":
        return trimmed_mean(grads, s, present=present)
    if mode == "multi_krum":
        return multi_krum(grads, s, present=present)
    if mode == "bulyan":
        return bulyan(grads, s, present=present)
    raise ValueError(f"unknown aggregation mode: {mode}")
