"""Adaptive coding autopilot — incident-driven runtime control (ROADMAP
item 5's payoff; importable WITHOUT jax, like the rest of the host side).

Every run used to execute one fixed (code family, redundancy, wire dtype)
point chosen at launch. The committed straggler study shows why that is
wrong for a time-varying fleet: exact cyclic r=3 wastes ~2× fleet compute
on a quiet fleet, while approx r=1.5 is the ONLY feasible family at 37.5%
drop rates — and neither can defend the other's regime. This module closes
the loop: a host-side policy engine that consumes the typed, attributed
incident stream (obs/incidents.py, PR 13) at chunk boundaries and emits
**remediations**:

  quarantine   a trust-collapsed worker is excluded via the present-mask
               schedule (its rows become erasures at a known position —
               the decode budget absorbs it, the aggregate never sees it)
               and the effective error budget is re-reported
  dial_down    sustained ``straggle``/``starvation`` episodes with the
               adversary signals quiet: swap exact cyclic r=2s+1 down to
               the approx family at ``r_low`` (arXiv:1905.05383 /
               arXiv:2006.09638 ground the residual bound the dial
               accepts — the decode_residual_bound column referees it
               per step)
  dial_up      the straggle evidence stays clear: swap back to the exact
               base family, restoring the Byzantine certificate
  readmit      a quarantined worker earns parole after a sustained clean
               window (its ledger trust resets to ``parole_trust`` so it
               is judged on fresh evidence)
  shadow_off   a ``numerics_drift`` episode drops the shadow wire dtype

Hysteresis both directions, like the detectors: every dial counts
consecutive chunk boundaries of evidence, so a single noisy window can
neither dial down nor dial back up, and ``max_swaps`` hard-caps regime
flapping.

Family/shape changes are **warm program swaps**: the :class:`Autopilot`
caches each regime's built setup, so switching INTO a new regime compiles
exactly that regime's program once (the compile sentinel counts it under
its own ``train_many@<regime>`` label) and returning to a previously-run
regime reuses its jitted executable — steady state within a regime stays
0-retrace under ``compile_guard="raise"``. Quarantine/readmit touch only
host schedule arrays: no program change at all.

Every decision is itself an attributed ``remediation`` event appended to
the run's ``incidents.jsonl`` (same stream, same seq counter — the
decision names the incident episode that triggered it) and a ``control``
block in status.json, so the control loop is as observable as the faults
it reacts to. ``tools/autopilot_study.py`` commits the proof: under a
time-varying adversary + churn scenario the autopilot reaches the target
loss on less fleet compute than every fixed configuration.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Dict, Optional

# boundary-hysteresis policy knobs; every key overridable per run via
# ``cfg.autopilot_policy`` ("key=value,..." — parse_policy validates)
DEFAULT_POLICY: Dict[str, float] = {
    # quarantine: a PRESENT worker whose EW trust (obs/forensics) sits
    # under the floor while a trust incident names it
    "trust_floor": 0.5,
    # max workers quarantined at once; -1 derives it from the code's own
    # erasure budget minus the configured straggler load and one unit of
    # churn headroom (see _quarantine_budget)
    "quarantine_budget": -1.0,
    # boundaries a quarantined worker waits before parole, and the trust
    # its ledger row resets to on re-admission
    "readmit_boundaries": 8.0,
    "parole_trust": 0.75,
    # dial-down: consecutive boundaries with an open straggle/starvation
    # episode AND this many adversary-quiet boundaries
    "dial_down_boundaries": 2.0,
    "clean_boundaries": 2.0,
    # dial-up: consecutive boundaries with the straggle evidence clear
    "dial_up_boundaries": 3.0,
    # the approx redundancy the dial-down accepts (fleet compute per step
    # drops from r=2s+1 to this; the analytic residual bound prices it)
    "r_low": 1.5,
    # hard cap on regime swaps per run — the anti-flap backstop on top of
    # the boundary hysteresis
    "max_swaps": 8.0,
    # boundaries of numerics_drift before the shadow dtype is dropped
    "shadow_off_boundaries": 1.0,
    # REAL-wire dial (ISSUE 15): boundaries of numerics_drift /
    # decode_residual evidence before the wire dtype widens one f32-ward
    # step (int8 → bf16 → f32), and boundaries of clean evidence before it
    # narrows one step back toward the configured dtype
    "wire_widen_boundaries": 1.0,
    "wire_narrow_boundaries": 4.0,
    # streaming-segment dial (ISSUE 16): boundaries of straggle evidence
    # before the wire segment count doubles (decode-on-arrival shortens
    # the tail a slow worker's last byte adds), capped at segments_max;
    # boundaries of straggle-quiet evidence before it halves back toward
    # the configured count (never past it). The segment dial fires BEFORE
    # the family dial-down — it keeps the exactness certificate, so it is
    # the cheap first rung of the straggler escalation ladder.
    "segments_up_boundaries": 1.0,
    "segments_down_boundaries": 4.0,
    "segments_max": 4.0,
    # tree-fanout dial (ISSUE 17): SECOND rung of the straggler ladder —
    # once the segment dial is maxed and straggle evidence persists, the
    # tree fanout halves (each combine node waits on fewer children, so a
    # slow child stalls a smaller subtree), never past fanout_min; sustained
    # straggle-quiet evidence doubles it back toward the configured fanout.
    # Same family, warm cached program swaps under `_g{fanout}` tags. Only
    # live when the run was launched with --topology tree.
    "fanout_down_boundaries": 2.0,
    "fanout_up_boundaries": 4.0,
    "fanout_min": 2.0,
}

# incident types that count as ADVERSARY evidence: any of these open (or
# new accusations landing in the ledger) vetoes a dial-down and resets the
# clean-window counter
_ADVERSARY_TYPES = ("trust", "guard", "nonfinite", "decode_residual")
_STRAGGLE_TYPES = ("straggle", "starvation")


def parse_policy(spec: str) -> Dict[str, float]:
    """``"r_low=1.2,clean_boundaries=3"`` -> override dict; unknown keys
    are config-time errors (DEFAULT_POLICY is the contract)."""
    out: Dict[str, float] = {}
    for item in (spec or "").split(","):
        item = item.strip()
        if not item:
            continue
        try:
            key, val = item.split("=", 1)
            key = key.strip()
            fval = float(val)
        except ValueError:
            raise ValueError(
                f"autopilot policy {item!r} is not '<key>=<float>'")
        if key not in DEFAULT_POLICY:
            raise ValueError(
                f"unknown autopilot policy key {key!r} (known: "
                f"{', '.join(sorted(DEFAULT_POLICY))})")
        out[key] = fval
    return out


@dataclasses.dataclass(frozen=True)
class Regime:
    """One point of the (family, redundancy, wire dtype) dial. For cyclic
    ``redundancy`` is the per-worker load r = 2s+1; for approx it is the
    fractional code_redundancy. ``wire_dtype`` (ISSUE 15) is the REAL
    wire's materialized dtype — the wire_widen/wire_narrow remediations
    move it along the f32 ↔ bf16 ↔ int8 ladder as warm cached program
    swaps, exactly like the family dial."""

    approach: str
    redundancy: float
    shadow_wire: str
    wire_dtype: str = "f32"
    # streaming segmented wire (ISSUE 16): the segments_up/segments_down
    # remediations move this along 1 ↔ 2 ↔ 4 ... (capped by policy
    # segments_max) as warm cached program swaps
    wire_segments: int = 1
    # tree topology (ISSUE 17): the leaf-group fan-in, 0 = flat. The
    # fanout_down/fanout_up remediations halve/double it along
    # base ↔ ... ↔ fanout_min as warm cached program swaps
    tree_fanout: int = 0

    @property
    def tag(self) -> str:
        t = f"{self.approach}_r{self.redundancy:g}"
        if self.shadow_wire != "off":
            t += f"_{self.shadow_wire}"
        if self.wire_dtype != "f32":
            t += f"_wire{self.wire_dtype}"
        if self.wire_segments != 1:
            t += f"_seg{self.wire_segments}"
        if self.tree_fanout:
            t += f"_g{self.tree_fanout}"
        return t

    def as_dict(self) -> dict:
        return {"approach": self.approach, "redundancy": self.redundancy,
                "shadow_wire": self.shadow_wire,
                "wire_dtype": self.wire_dtype,
                "wire_segments": self.wire_segments,
                "tree_fanout": self.tree_fanout, "tag": self.tag}


def base_regime(cfg) -> Regime:
    r = (2 * cfg.worker_fail + 1 if cfg.approach == "cyclic"
         else float(cfg.code_redundancy))
    fanout = (int(cfg.tree_fanout)
              if getattr(cfg, "topology", "flat") == "tree" else 0)
    return Regime(cfg.approach, float(r), cfg.shadow_wire,
                  getattr(cfg, "wire_dtype", "f32"),
                  int(getattr(cfg, "wire_segments", 1)), fanout)


def regime_cfg(base_cfg, regime: Regime, quarantined: int = 0):
    """The TrainConfig a regime's program is built from. Schedule/host
    fault kinds are stripped (they were applied to the host schedules at
    launch and never live inside a compiled program); in-graph kinds stay
    so nan/inf injection survives a swap. The approx regime drops the
    Byzantine knobs (validate: no certificate) and sizes its straggler
    design point to cover the quarantined workers plus churn headroom."""
    from draco_tpu.resilience.faults import INGRAPH_KINDS, plan_from_cfg

    kw = {"approach": regime.approach, "shadow_wire": regime.shadow_wire,
          "wire_dtype": regime.wire_dtype,
          "wire_segments": regime.wire_segments}
    # tree topology rides the regime (ISSUE 17): a dialed fanout keeps the
    # family's tree shape; depth re-derives (auto) when the fanout moved
    # off the launch value, since the pinned level count may be infeasible
    # at the new group count
    if regime.tree_fanout:
        kw["topology"] = "tree"
        kw["tree_fanout"] = regime.tree_fanout
        if regime.tree_fanout != int(getattr(base_cfg, "tree_fanout", 0)):
            kw["tree_levels"] = 0
    else:
        kw["topology"] = "flat"
    plan = plan_from_cfg(base_cfg)
    if plan is not None:
        kw["fault_spec"] = ",".join(ev.spec() for ev in plan.events
                                    if ev.kind in INGRAPH_KINDS)
    if regime.approach == "approx":
        n = base_cfg.num_workers
        alpha = max(
            base_cfg.straggler_alpha,
            min(0.9, (quarantined + base_cfg.straggle_count + 1) / n),
        )
        kw.update(worker_fail=0, adversary_count=0, redundancy="shared",
                  code_redundancy=float(regime.redundancy),
                  assignment_scheme="pairwise", straggler_alpha=alpha)
    elif regime.approach == "cyclic":
        kw.update(worker_fail=base_cfg.worker_fail,
                  adversary_count=base_cfg.adversary_count,
                  redundancy=base_cfg.redundancy)
    return dataclasses.replace(base_cfg, **kw)


class Autopilot:
    """The policy engine: :meth:`act` runs at every chunk-boundary flush
    (control/engine.py), reading the incident engine + accusation ledger
    the heartbeat already feeds, and actuating through the engine's client
    (quarantine/readmit = schedule writes; regime swaps = warm cached
    program switches)."""

    def __init__(self, cfg, heartbeat, policy: Optional[dict] = None,
                 dim: Optional[int] = None):
        self.cfg = cfg
        self.heartbeat = heartbeat
        self.incidents = heartbeat.incidents  # IncidentEngine (required)
        self.policy = dict(DEFAULT_POLICY)
        self.policy.update(policy or {})
        self.base = base_regime(cfg)
        self.regime = self.base
        self.dim = dim
        self._setups: dict = {}  # Regime -> built setup (warm swap cache)
        # worker -> {"step", "boundaries", "trigger"} while quarantined
        self.quarantined: Dict[int, dict] = {}
        # readmitted workers whose restored schedule has not yet SHOWN
        # them present (the engine's two-chunk assembly pipeline lags the
        # remediation): they stay excluded from the straggle detector
        # until a present record lands, else parole would fire a spurious
        # straggle incident
        self._paroled: Dict[int, int] = {}
        self.remediations: list = []
        self.swaps = 0
        self._adv_quiet = 0
        self._strag_hot = 0
        self._strag_quiet = 0
        self._drift_hot = 0
        self._wire_hot = 0
        self._wire_quiet = 0
        self._prev_accused = 0.0

    def attach(self, client) -> None:
        """Engine-construction hook: seed the warm-swap cache with the
        loop's base setup and, when the autopilot already sits in a
        non-base regime (a later run() call on the same Trainer), switch
        the fresh client onto it before the first dispatch."""
        setup = getattr(client, "setup", None)
        if setup is not None:
            self._setups.setdefault(self.base, setup)
        if self.regime != self.base and self.regime in self._setups:
            client.switch_regime(
                self._setups[self.regime],
                f"{client.BASE_LABEL}@{self.regime.tag}")

    # ---- evidence --------------------------------------------------------
    def _quarantine_budget(self) -> int:
        b = self.policy["quarantine_budget"]
        if b >= 0:
            return int(b)
        cfg = self.cfg
        if self.base.approach == "cyclic":
            # erasure-only budget e <= 2s, minus the configured straggler
            # load, minus one unit of churn headroom
            return max(0, 2 * cfg.worker_fail - cfg.straggle_count - 1)
        return max(0, math.ceil(cfg.straggler_alpha * cfg.num_workers)
                   - cfg.straggle_count - 1)

    def _open(self) -> Dict[str, dict]:
        return {e["type"]: e for e in self.incidents.open_episodes()}

    # ---- actuation -------------------------------------------------------
    def act(self, step: int, engine) -> None:
        """One chunk-boundary decision pass. ``engine`` is the live
        ChunkedEngine; its client is the actuation surface."""
        client = engine.client
        # parole completes when the readmitted worker is OBSERVED present
        # again (the newest record's masks) — only then does its absence
        # become telemetry for the straggle detector
        masks = self.incidents.current_masks
        for w in list(self._paroled):
            if masks is not None and masks["present"][w]:
                self.incidents.quarantined.discard(w)
                del self._paroled[w]
        open_eps = self._open()
        ledger = self.incidents.ledger

        # adversary-quiet window: no adversary-class episode open and no
        # NEW accusations since the last boundary
        accused = float(sum(ledger.accused)) if ledger is not None else 0.0
        adversary_evidence = (
            any(t in open_eps for t in _ADVERSARY_TYPES)
            or accused > self._prev_accused)
        self._prev_accused = accused
        self._adv_quiet = 0 if adversary_evidence else self._adv_quiet + 1

        straggle_evidence = any(t in open_eps for t in _STRAGGLE_TYPES)
        self._strag_hot = self._strag_hot + 1 if straggle_evidence else 0
        self._strag_quiet = 0 if straggle_evidence else self._strag_quiet + 1
        self._drift_hot = (self._drift_hot + 1
                           if "numerics_drift" in open_eps else 0)
        # REAL-wire evidence (ISSUE 15): numerics drift on the wire columns
        # or decode-residual drift (residual-near-bound / rel-tol crossing)
        # argues the narrow dtype's noise floor is no longer safe
        wire_evidence = ("numerics_drift" in open_eps
                        or "decode_residual" in open_eps)
        self._wire_hot = self._wire_hot + 1 if wire_evidence else 0
        self._wire_quiet = 0 if wire_evidence else self._wire_quiet + 1

        self._maybe_quarantine(step, client, open_eps, ledger)
        self._maybe_readmit(step, client, ledger)
        if getattr(client, "can_swap", True) \
                and self.swaps < self.policy["max_swaps"]:
            from draco_tpu.obs.numerics import WIRE_WIDEN, narrow_toward

            if (self.regime.wire_dtype != "f32"
                    and self._wire_hot
                    >= self.policy["wire_widen_boundaries"]):
                # wire_widen (ISSUE 15): the dial moves the REAL wire one
                # f32-ward step — a warm cached program swap like every
                # other regime change; the narrow dtype's noise floor is
                # implicated by the open drift/residual episode
                trigger = (open_eps.get("numerics_drift")
                           or open_eps.get("decode_residual"))
                target = dataclasses.replace(
                    self.regime,
                    wire_dtype=WIRE_WIDEN[self.regime.wire_dtype])
                self._swap(step, client, target, "wire_widen", trigger, {
                    "wire_evidence_boundaries": self._wire_hot,
                    "wire_dtype_before": self.regime.wire_dtype,
                    "wire_dtype_after": target.wire_dtype,
                })
            elif (self.regime.wire_dtype != self.base.wire_dtype
                  and self._wire_quiet
                  >= self.policy["wire_narrow_boundaries"]
                  and narrow_toward(self.regime.wire_dtype,
                                    self.base.wire_dtype)
                  != self.regime.wire_dtype):
                # wire_narrow: sustained clean evidence earns one step back
                # toward the configured narrow dtype (never past it)
                trigger = self._last_cleared(("numerics_drift",
                                              "decode_residual"))
                target = dataclasses.replace(
                    self.regime,
                    wire_dtype=narrow_toward(self.regime.wire_dtype,
                                             self.base.wire_dtype))
                self._swap(step, client, target, "wire_narrow", trigger, {
                    "wire_quiet_boundaries": self._wire_quiet,
                    "wire_dtype_before": self.regime.wire_dtype,
                    "wire_dtype_after": target.wire_dtype,
                })
            elif self._drift_hot >= self.policy["shadow_off_boundaries"] \
                    and self.regime.shadow_wire != "off":
                self._swap(step, client,
                           dataclasses.replace(self.regime,
                                               shadow_wire="off"),
                           "shadow_off", open_eps.get("numerics_drift"),
                           {"drift_boundaries": self._drift_hot})
            elif (self.regime.approach in ("cyclic", "approx")
                  and self._strag_hot
                  >= self.policy["segments_up_boundaries"]
                  and self.regime.wire_segments
                  < int(self.policy["segments_max"])):
                # segments_up (ISSUE 16): the first rung of the straggler
                # ladder — double the wire segment count so the aggregator
                # decodes segments on arrival instead of waiting for the
                # slowest worker's LAST byte. Keeps the family (and its
                # exactness certificate); the family dial-down only fires
                # once the segment dial is maxed out.
                trigger = (open_eps.get("straggle")
                           or open_eps.get("starvation"))
                target = dataclasses.replace(
                    self.regime,
                    wire_segments=min(max(2 * self.regime.wire_segments, 2),
                                      int(self.policy["segments_max"])))
                self._swap(step, client, target, "segments_up", trigger, {
                    "straggle_boundaries": self._strag_hot,
                    "wire_segments_before": self.regime.wire_segments,
                    "wire_segments_after": target.wire_segments,
                })
            elif (self.regime.tree_fanout
                  and self._strag_hot
                  >= self.policy["fanout_down_boundaries"]
                  and self.regime.tree_fanout % 2 == 0
                  and self.regime.tree_fanout // 2
                  >= int(self.policy["fanout_min"])
                  and self._fanout_ok(self.regime.tree_fanout // 2)):
                # fanout_down (ISSUE 17): the straggler ladder's SECOND
                # rung — the segment dial is maxed (or spent) and straggle
                # persists, so the tree fanout halves: every combine node
                # waits on half the children, shrinking the subtree one
                # slow worker can stall. Same family, same certificate;
                # a warm cached program swap under the `_g{fanout}` tag.
                trigger = (open_eps.get("straggle")
                           or open_eps.get("starvation"))
                target = dataclasses.replace(
                    self.regime, tree_fanout=self.regime.tree_fanout // 2)
                self._swap(step, client, target, "fanout_down", trigger, {
                    "straggle_boundaries": self._strag_hot,
                    "tree_fanout_before": self.regime.tree_fanout,
                    "tree_fanout_after": target.tree_fanout,
                })
            elif (self.regime.approach == "cyclic"
                  and self._strag_hot >= self.policy["dial_down_boundaries"]
                  and self._adv_quiet >= self.policy["clean_boundaries"]
                  and self._dial_down_allowed(step)):
                trigger = (open_eps.get("straggle")
                           or open_eps.get("starvation"))
                target = Regime("approx", float(self.policy["r_low"]),
                                self.regime.shadow_wire,
                                self.regime.wire_dtype,
                                tree_fanout=self.regime.tree_fanout)
                self._swap(step, client, target, "dial_down", trigger, {
                    "straggle_boundaries": self._strag_hot,
                    "adversary_quiet_boundaries": self._adv_quiet,
                    "fleet_load_before": self.regime.redundancy,
                    "fleet_load_after": target.redundancy,
                    # what the dial accepts: bounded decode error instead
                    # of exactness — refereed per step by the
                    # decode_residual <= decode_residual_bound certificate
                    "accepted_bound": "optimal-decoding residual bound "
                                      "(arXiv:2006.09638), per-step column "
                                      "decode_residual_bound",
                })
            elif (self.regime.approach == "approx"
                  and self.base.approach == "cyclic"
                  and self._strag_quiet >= self.policy["dial_up_boundaries"]):
                trigger = self._last_cleared(_STRAGGLE_TYPES)
                self._swap(step, client,
                           dataclasses.replace(self.base,
                                               shadow_wire=self.regime
                                               .shadow_wire,
                                               wire_dtype=self.regime
                                               .wire_dtype,
                                               wire_segments=self.regime
                                               .wire_segments),
                           "dial_up", trigger, {
                               "straggle_quiet_boundaries":
                                   self._strag_quiet,
                               "restores": "exact decode + Byzantine "
                                           "certificate",
                           })
            elif (self.regime.tree_fanout and self.base.tree_fanout
                  and self.regime.tree_fanout < self.base.tree_fanout
                  and self._strag_quiet
                  >= self.policy["fanout_up_boundaries"]):
                # fanout_up: sustained straggle-quiet evidence doubles the
                # fanout back toward the configured one (never past it) —
                # wider groups restore the per-group budget s_g and cut
                # the level count on a quiet fleet
                trigger = self._last_cleared(_STRAGGLE_TYPES)
                target = dataclasses.replace(
                    self.regime,
                    tree_fanout=min(2 * self.regime.tree_fanout,
                                    self.base.tree_fanout))
                self._swap(step, client, target, "fanout_up", trigger, {
                    "straggle_quiet_boundaries": self._strag_quiet,
                    "tree_fanout_before": self.regime.tree_fanout,
                    "tree_fanout_after": target.tree_fanout,
                })
            elif (self.regime.wire_segments > self.base.wire_segments
                  and self._strag_quiet
                  >= self.policy["segments_down_boundaries"]):
                # segments_down: sustained straggle-quiet evidence halves
                # the segment count back toward the configured one (never
                # past it) — single-message wires pay no per-segment
                # locator overhead on a quiet fleet
                trigger = self._last_cleared(_STRAGGLE_TYPES)
                target = dataclasses.replace(
                    self.regime,
                    wire_segments=max(self.regime.wire_segments // 2,
                                      self.base.wire_segments))
                self._swap(step, client, target, "segments_down", trigger, {
                    "straggle_quiet_boundaries": self._strag_quiet,
                    "wire_segments_before": self.regime.wire_segments,
                    "wire_segments_after": target.wire_segments,
                })
        self.heartbeat.set_control(self.status_block())

    def _fanout_ok(self, fanout: int) -> bool:
        """A dialed fanout must keep a buildable tree (divisibility, ≥2
        groups) and — for cyclic — a per-group budget s_g that still
        carries the DECLARED adversary load (the worst case lands every
        adversary in one leaf group, config.validate's rule mirrored
        dynamically)."""
        from draco_tpu.coding.topology import group_worker_fail, tree_plan

        try:
            tree_plan(self.cfg.num_workers, fanout)
        except ValueError:
            return False
        if self.regime.approach == "cyclic":
            s_g = group_worker_fail(fanout, self.cfg.worker_fail)
            if self.cfg.num_adversaries > s_g:
                return False
        return True

    def _dial_down_allowed(self, step: int) -> bool:
        """The approx family cannot express a Byzantine attack — the
        simulation injects nothing there, which is exactly why
        config.validate rejects adversary/over_budget fault kinds under
        approach=approx. The dial must mirror that rule dynamically: a
        run whose DECLARED scenario still schedules Byzantine activity
        beyond ``step`` (a live seeded adversary count, or a fault-plan
        adversary/over_budget occurrence ahead) may not dial into a
        regime where those events would be silently inert."""
        from draco_tpu.resilience.faults import plan_from_cfg

        if self.cfg.num_adversaries > 0:
            return False
        plan = plan_from_cfg(self.cfg)
        if plan is not None:
            for ev in plan.of_kind("adversary", "over_budget"):
                if ev.last_step > step:
                    return False
        return True

    def _maybe_quarantine(self, step, client, open_eps, ledger) -> None:
        if ledger is None:
            return
        trigger = open_eps.get("trust")
        if trigger is None:
            return  # the decision must have an incident to attribute to
        floor = self.policy["trust_floor"]
        candidates = sorted(
            (w for w in range(ledger.n)
             if ledger.trust[w] < floor and w not in self.quarantined),
            key=lambda w: ledger.trust[w])
        if not candidates:
            return
        if len(self.quarantined) >= self._quarantine_budget():
            return  # out of erasure budget: the guard keeps the run safe
        w = candidates[0]
        client.quarantine(w, from_step=step + 1)
        self.incidents.quarantined.add(w)
        self.quarantined[w] = {"step": step, "boundaries": 0,
                               "trigger": trigger}
        self._remediate("quarantine", step, trigger, worker=w, evidence={
            "trust": round(ledger.trust[w], 4), "trust_floor": floor,
            # the s rebudget: the worker is an erasure now — report the
            # budget the decode is left with
            "quarantined_total": len(self.quarantined),
            "erasure_budget": self._quarantine_budget(),
            # the engine's next chunk was assembled before this boundary:
            # the schedule write lands at effective_step, the wire sees
            # it one chunk later (PERF.md §16)
            "wire_lag": "one assembled chunk",
        })

    def reapply_quarantines(self, schedule) -> None:
        """Re-stamp every ACTIVE quarantine onto a freshly (re)generated
        present-mask schedule — Trainer._ensure_schedules rebuilds the
        tables when a block-wise run() overruns them, and a regenerated
        table must not silently re-admit a worker the policy still holds
        excluded."""
        for w in self.quarantined:
            schedule[:, w] = True

    def _maybe_readmit(self, step, client, ledger) -> None:
        for w in list(self.quarantined):
            info = self.quarantined[w]
            info["boundaries"] += 1
            if info["boundaries"] < self.policy["readmit_boundaries"] \
                    or self._adv_quiet < self.policy["clean_boundaries"]:
                continue
            client.readmit(w, from_step=step + 1)
            # stays in incidents.quarantined until observed present again
            self._paroled[w] = step
            if ledger is not None:
                ledger.forgive(w, self.policy["parole_trust"])
            del self.quarantined[w]
            self._remediate("readmit", step, info["trigger"], worker=w,
                            evidence={
                                "quarantined_boundaries": info["boundaries"],
                                "adversary_quiet_boundaries":
                                    self._adv_quiet,
                                "parole_trust": self.policy["parole_trust"],
                            })

    def _swap(self, step, client, target: Regime, action, trigger,
              evidence) -> None:
        setup = self._setups.get(target)
        warm = setup is not None
        if setup is None:
            # provision the regime for the WORST quarantine load the
            # policy can ever reach (_quarantine_budget), not the current
            # count: the setup is cached per regime, and a later re-entry
            # with more workers quarantined must still sit inside the
            # approx straggler design point it was built with
            setup = client.build_setup(
                regime_cfg(self.cfg, target, self._quarantine_budget()))
            self._setups[target] = setup
        label = (client.BASE_LABEL if target == self.base
                 else f"{client.BASE_LABEL}@{target.tag}")
        client.switch_regime(setup, label)
        # keep the engine's dispatch-span segment tag in step with the
        # regime actually dispatched (segments_up/segments_down swaps)
        client.wire_segments = target.wire_segments
        prev, self.regime = self.regime, target
        self.swaps += 1
        # counters reset so the NEW regime earns its own evidence window
        self._strag_hot = self._strag_quiet = self._drift_hot = 0
        self._wire_hot = self._wire_quiet = 0
        try:
            # the wire ledger is per-family: re-stamp the status block
            from draco_tpu.obs import numerics as numerics_mod

            dim = getattr(setup, "dim", None) or self.dim
            if dim:
                self.heartbeat.set_wire(numerics_mod.wire_ledger(
                    regime_cfg(self.cfg, target, len(self.quarantined)),
                    dim))
        except Exception:
            pass
        ev = dict(evidence or {})
        ev["executable"] = "reused" if warm else "compiled"
        self._remediate(action, step, trigger,
                        regime=target, evidence=ev,
                        regime_from=prev)

    def _last_cleared(self, types) -> Optional[dict]:
        """The most recently CLOSED episode of ``types`` — the attribution
        for a recovery decision (the condition whose clearing earned it)."""
        for ep in reversed(self.incidents.episodes):
            if ep["type"] in types:
                return dict(ep, cleared=True)
        return None

    # ---- reporting -------------------------------------------------------
    def _remediate(self, action, step, trigger, worker=None, regime=None,
                   evidence=None, regime_from=None) -> None:
        rem = {
            "action": action, "step": int(step),
            # wall-clock stamp (ISSUE 19): MTTR = remediation ts − onset
            # ts, joined offline by obs/fleet — stamped here too so the
            # ``control`` status block's ``last`` carries it even though
            # the incidents stream stamps its own copy per line
            "ts": time.time(),
            "effective_step": int(step) + 1,
            "worker": worker,
            "regime": regime.as_dict() if regime is not None else None,
            "regime_from": (regime_from.as_dict()
                            if regime_from is not None else None),
            "trigger": ({
                "type": trigger.get("type"),
                "severity": trigger.get("severity"),
                "onset_step": trigger.get("onset_step"),
                "workers": trigger.get("workers"),
                "cleared": bool(trigger.get("cleared", False)),
            } if trigger else None),
            "evidence": dict(evidence or {}),
        }
        self.remediations.append(rem)
        self.incidents.remediation(rem)
        self.heartbeat.set_control(self.status_block())

    def status_block(self) -> dict:
        """The ``control`` status.json block (additive under schema 4)."""
        return {
            "autopilot": "on",
            "regime": self.regime.as_dict(),
            "base_regime": self.base.tag,
            "swaps": self.swaps,
            "quarantined": sorted(self.quarantined),
            "remediations": len(self.remediations),
            "last": (self.remediations[-1] if self.remediations else None),
        }


def make_autopilot(cfg, heartbeat, dim: Optional[int] = None
                   ) -> Optional[Autopilot]:
    """The one construction rule both production loops share: an autopilot
    only when ``cfg.autopilot == "on"`` AND the incident engine is live on
    this process (the sensing layer it actuates on — config.validate pins
    the dependency, this guards the non-main multihost processes)."""
    if getattr(cfg, "autopilot", "off") != "on" \
            or heartbeat.incidents is None:
        return None
    return Autopilot(cfg, heartbeat,
                     policy=parse_policy(getattr(cfg, "autopilot_policy",
                                                 "")),
                     dim=dim)
