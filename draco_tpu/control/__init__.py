"""Runtime control (ROADMAP item 5): the unified chunked host engine both
production loops run on (:mod:`draco_tpu.control.engine`) and the adaptive
coding autopilot that re-selects (code family, redundancy, wire dtype) at
chunk boundaries from the live incident stream
(:mod:`draco_tpu.control.autopilot`)."""

from draco_tpu.control.engine import ChunkedEngine  # noqa: F401
