"""The ONE scan-chunked host loop — ``ChunkedEngine`` (ROADMAP item 5).

Until this module the coded-DP CNN Trainer (training/trainer.py) and the
shared LM token loop (parallel/token_loop.py) each carried a private copy
of the same host machinery around their K-fused ``lax.scan`` dispatch:
double-buffered chunk assembly, deferred (K, m) metric blocks, the
eval/checkpoint chunk-boundary snapping, the host span tracer, the
compile/retrace sentinel, the heartbeat beat, the graceful-stop poll, and
the profiler capture window. PR 10's ``metric_family_names`` proved the
seam by unifying the column declarations; this engine unifies the loop
itself. Each loop now contributes only a thin *client* — what a chunk's
payload IS (stacked image batches vs token blocks vs a step-index vector),
how to dispatch it, and what happens at an eval/checkpoint boundary — and
the engine owns everything that must behave identically: the flush
cadence, the t_fetch/t_comp accounting (CNN loop), the stop/snap
discipline, and the chunk-boundary **autopilot hook**
(draco_tpu/control/autopilot.py) that this refactor exists to unlock.

Client protocol (duck-typed; both implementations live next to their
loops):

  label           compile-watch program label for the CURRENT regime
                  ("train_many" / "train_token_many"; regime swaps append
                  a suffix so each regime warms its own window)
  metric_names    column order of the current regime's metric block
                  (re-read per chunk — a family swap changes it)
  assemble(i, ranges)         build + upload chunk i's payload (client
                              does its own gather/upload tracer spans and
                              double-buffering)
  dispatch(state, payload)    run the chunk program -> (state, block)
  defer_extras(payload, fetch_s, k)  extra per-chunk record fields
                              (t_fetch, present counts) or None
  should_log(step)            the loop's metrics.jsonl cadence
  beat_extras()               heartbeat extras (prefetch depth/restarts)
  boundary(end, state)        eval + checkpoint at an eval_freq boundary
  stop_requested(end)         graceful-stop poll (fires pending fault-plan
                              sigterm events through the real handler)
  snap_stop(end, state, already_saved)  resumable checkpoint + bookkeeping
  cleanup()                   always runs on exit (close prefetchers)

Equivalence contract: with the autopilot off this engine reproduces the
two historical loops' observable behavior exactly — same trace span names
and nesting, same compile-watch labels, same flush cadence, same record
schema — pinned by the committed K ∈ {1, 4} bitwise suites running
unchanged on it (``compile_guard="raise"``, 0 steady retraces).
"""

from __future__ import annotations

import time
from typing import Optional

from draco_tpu.obs import profiler_window
from draco_tpu.utils.metrics import DeferredMetricWriter


class ChunkedEngine:
    """Run the chunked regime over ``ranges`` with ``client`` supplying the
    loop-specific pieces. ``timed=True`` adds the CNN loop's t_fetch/t_comp
    wall accounting (a ``sync`` span + per-flush ``t_comp`` record field);
    the LM loop runs untimed (its flush IS the sync, PERF.md §0).

    ``autopilot`` (control/autopilot.py, or None) acts at every flush
    boundary — AFTER the heartbeat beat, so the incident engine has folded
    every record and beat signal up to that step. The engine exposes
    ``state`` / ``last_end`` live so an escalated stop
    (resilience.supervisor.ImmediateStopError) can checkpoint the newest
    dispatched state without waiting for the next boundary.
    """

    def __init__(self, client, *, eval_freq: int, total_end: int,
                 tracer, heartbeat, compile_watch, writer,
                 autopilot=None, timed: bool = False,
                 profile_dir: Optional[str] = None,
                 profile_steps: tuple = (3, 8), is_main: bool = True):
        self.client = client
        self.eval_freq = eval_freq
        self.total_end = total_end
        self.tracer = tracer
        self.heartbeat = heartbeat
        self.compile_watch = compile_watch
        self.autopilot = autopilot
        self.timed = timed
        self.profile_dir = profile_dir
        self.profile_steps = profile_steps
        self.is_main = is_main
        self.deferred = DeferredMetricWriter(writer,
                                             observer=heartbeat.observe)
        if autopilot is not None:
            # regime/quarantine state outlives loop objects: re-point the
            # fresh client at the autopilot's current regime
            autopilot.attach(client)
        # newest dispatched state + its chunk-end step — the escalation
        # path's checkpoint source (supervisor.ImmediateStopError)
        self.state = None
        self.last_end: Optional[int] = None

    def run(self, state, ranges):
        """Drive chunks over ``ranges``; returns (state, last record)."""
        client, deferred = self.client, self.deferred
        tracer, heartbeat = self.tracer, self.heartbeat
        watch = self.compile_watch
        self.state = state
        if not ranges:
            return state, {}
        win = profiler_window(self.profile_dir, self.profile_steps,
                              self.is_main, tracer,
                              on_stop=heartbeat.observe_device)
        # t_fetch = the chunk's host assemble + upload wall; t_comp = the
        # flush window's remaining wall (device execution + drain)
        # amortized over its steps — same record keys as the eager loops
        window_t0 = time.perf_counter()
        window_fetch = 0.0
        window_steps = 0

        def upload(i):
            nonlocal window_fetch
            t0 = time.perf_counter()
            payload = client.assemble(i, ranges)
            dt = time.perf_counter() - t0
            window_fetch += dt
            return payload, dt

        try:
            chunk, fetch_s = upload(0)
            for i, (start, k) in enumerate(ranges):
                end = start + k - 1
                # capture snaps to whole chunks; the chunk start rides
                # along so the anchor's steps_profiled reflects the window
                win.maybe_start(end, first_step=start)
                # segmented wire (ISSUE 16): tag dispatch spans with the
                # live segment count ONLY when the regime actually splits
                # the wire — S=1 trace records stay byte-identical to the
                # pre-segmentation suites (the bitwise rail)
                span_kw = {"chunk_start": start, "k": k}
                seg = int(getattr(client, "wire_segments", 1) or 1)
                if seg > 1:
                    span_kw["segments"] = seg
                with tracer.span("dispatch", **span_kw), \
                        watch.expect(client.label, key=k):
                    state, block = client.dispatch(state, chunk)
                self.state, self.last_end = state, end
                deferred.defer(range(start, end + 1), client.metric_names,
                               block, client.defer_extras(chunk, fetch_s, k))
                window_steps += k
                if i + 1 < len(ranges):  # overlap: assemble i+1 during i
                    chunk, fetch_s = upload(i + 1)
                boundary = bool(self.eval_freq) \
                    and end % self.eval_freq == 0
                if boundary or i + 1 == len(ranges) or deferred.depth >= 4:
                    common = None
                    if self.timed:
                        # drain the window's chunks BEFORE reading the
                        # clock so device execution lands in t_comp (a
                        # device→host fetch, NOT block_until_ready — the
                        # latter only awaits dispatch on remote backends,
                        # PERF.md §0); this is the boundary's one true sync
                        with tracer.span("sync", at_step=end):
                            deferred.sync()
                        t_comp = max(time.perf_counter() - window_t0
                                     - window_fetch, 0.0)
                        common = {"t_comp": round(t_comp / window_steps, 6)}
                    with tracer.span("flush", at_step=end):
                        deferred.flush(client.should_log, common)
                        heartbeat.beat(end, self.total_end,
                                       extra={**client.beat_extras(),
                                              **watch.snapshot()})
                        tracer.flush()
                    window_t0 = time.perf_counter()
                    window_fetch = 0.0
                    window_steps = 0
                    if self.autopilot is not None:
                        # every record + beat up to ``end`` has been folded
                        # into the incident engine: decide remediations now,
                        # effective from the NEXT assembled chunk
                        self.autopilot.act(end, self)
                win.maybe_stop(end, state.params)
                if boundary:
                    client.boundary(end, state)
                    # eval/checkpoint wall must not leak into the next
                    # window's t_comp (the eager loops' Segments exclude
                    # them too)
                    window_t0 = time.perf_counter()
                if client.stop_requested(end):
                    # a chunk boundary is a legal stop point mid-window:
                    # drain the pending metric blocks first, then snap the
                    # resumable checkpoint exactly here
                    if self.timed:
                        with tracer.span("sync", at_step=end):
                            deferred.sync()
                    with tracer.span("flush", at_step=end):
                        deferred.flush(client.should_log)
                    client.snap_stop(end, state, bool(boundary))
                    break
        finally:
            try:
                win.stop(state.params)  # loop may end inside the window
            finally:
                client.cleanup()
        return state, deferred.last


class SegmentPipeline:
    """Decode-on-arrival driver over a segmented wire (ISSUE 16).

    The production chunked regime decodes segments IN-GRAPH
    (coding/cyclic.decode_segments / coding/approx.decode_segments — one
    jitted program, zero host seams), so nothing here sits on the training
    path. This driver is the measurement harness over the seam the wire
    actually crosses in a multi-host deployment: the per-segment
    host→device transfer of narrow codeword buffers. In ``pipelined``
    mode each loop turn async-dispatches segment ``j``'s decode, pushes
    segment ``j+1``'s transfer WHILE that decode executes, and only then
    drains ``j`` — so the transfer wall hides under the decode wall. The
    serial rail (``pipelined=False``) drains before the next transfer,
    forbidding overlap; the delta between the rails is the pipeline win
    tools/segment_study.py commits behind perf_watch (PERF.md §18).

    Hooks (duck-typed, like the engine's client protocol):

      put(j, host_segment) -> device buffer      (the wire transfer)
      decode(j, device buffer) -> result          (async dispatch — must
                                                  NOT block)
      drain(result) -> None                       (block until the decode
                                                  actually finished)

    Every hook call is wrapped in a tracer span (``segment_xfer`` /
    ``segment_decode`` / ``segment_drain``, each tagged ``segment=j``) and
    mirrored into ``self.events`` with host perf_counter stamps, so the
    study can both compute the overlap fraction in-process and merge the
    spans against a device-profiler capture (obs/device_attr
    .merge_timeline)."""

    def __init__(self, tracer, put, decode, drain=None, *,
                 pipelined: bool = True):
        self.tracer = tracer
        self.put = put
        self.decode = decode
        self.drain = drain
        self.pipelined = pipelined
        self.events = []  # [{name, segment, t0_s, t1_s}] host wall stamps

    def _timed(self, name, j, fn):
        t0 = time.perf_counter()
        with self.tracer.span(name, segment=j):
            out = fn()
        self.events.append({"name": name, "segment": j,
                            "t0_s": t0, "t1_s": time.perf_counter()})
        return out

    def run(self, host_segments):
        """Drive all segments; returns the per-segment decode results
        (drained when a ``drain`` hook was given)."""
        n = len(host_segments)
        results = []
        if n == 0:
            return results
        dev = self._timed("segment_xfer", 0,
                          lambda: self.put(0, host_segments[0]))
        for j in range(n):
            out = self._timed("segment_decode", j,
                              lambda j=j, dev=dev: self.decode(j, dev))
            if self.pipelined:
                # transfer j+1 rides under decode j's async execution;
                # the drain AFTER it is what exposes the overlap
                if j + 1 < n:
                    dev = self._timed(
                        "segment_xfer", j + 1,
                        lambda j=j: self.put(j + 1, host_segments[j + 1]))
                if self.drain is not None:
                    self._timed("segment_drain", j,
                                lambda out=out: self.drain(out))
            else:
                # serial rail: drain FIRST, so the next transfer cannot
                # overlap — the no-pipeline control
                if self.drain is not None:
                    self._timed("segment_drain", j,
                                lambda out=out: self.drain(out))
                if j + 1 < n:
                    dev = self._timed(
                        "segment_xfer", j + 1,
                        lambda j=j: self.put(j + 1, host_segments[j + 1]))
            results.append(out)
        return results

    def overlap_us(self):
        """(overlapped transfer µs, decode in-flight µs): each pipelined
        turn's in-flight window runs from decode ``j``'s dispatch end to
        its drain end; transfer ``j+1`` wall inside that window is wire
        time the pipeline hid. Serial runs report 0 overlap by
        construction (the drain precedes the transfer)."""
        by_seg = {}
        for ev in self.events:
            by_seg.setdefault(ev["segment"], {})[ev["name"]] = ev
        total_inflight = 0.0
        overlapped = 0.0
        for j, evs in sorted(by_seg.items()):
            dec, drn = evs.get("segment_decode"), evs.get("segment_drain")
            if dec is None or drn is None:
                continue
            lo, hi = dec["t1_s"], drn["t1_s"]
            total_inflight += max(hi - lo, 0.0)
            nxt = by_seg.get(j + 1, {}).get("segment_xfer")
            if nxt is not None:
                overlapped += max(min(nxt["t1_s"], hi)
                                  - max(nxt["t0_s"], lo), 0.0)
        return overlapped * 1e6, total_inflight * 1e6
