"""ChunkedEngine clients — the loop-specific halves of the unified
chunked host loop (control/engine.py), one per production loop.

Each client owns exactly what its loop is ABOUT: what a chunk payload is
(stacked image batches + masks for the coded-DP Trainer, token blocks or
a (K,) step vector for the LM routes), how to dispatch it, and what an
eval/checkpoint boundary does. Everything both loops must do identically
(flush cadence, deferred metrics, stop/snap discipline, profiler
windows, heartbeat beats, the autopilot hook) lives in the engine.

The clients are also the autopilot's actuation surface
(control/autopilot.py): ``switch_regime`` swaps the dispatched setup —
warm, because the autopilot caches built setups per regime, so a return
swap reuses the jitted executable — and ``quarantine``/``readmit``
mutate the present-mask schedule the next assembled chunk reads (an
erasure at a known position; no program change at all).
"""

from __future__ import annotations

import numpy as np


class TrainerChunkClient:
    """Client for the coded-DP CNN Trainer (training/trainer.py): a chunk
    payload is the stacked (xs, ys, masks, presents) upload."""

    BASE_LABEL = "train_many"

    def __init__(self, tr):
        self.tr = tr
        self.label = self.BASE_LABEL
        self.setup = tr.setup
        # current regime's wire segmentation (ISSUE 16) — re-stamped by the
        # autopilot on segments_up/segments_down swaps so the engine's
        # dispatch spans carry the live S
        self.wire_segments = int(getattr(tr.cfg, "wire_segments", 1) or 1)
        self._pre_quarantine = {}  # worker -> schedule column before it

    @property
    def metric_names(self):
        return self.setup.metric_names

    def assemble(self, i, ranges):
        return self.tr._device_chunk(
            ranges[i], ranges[i + 1] if i + 1 < len(ranges) else None)

    def dispatch(self, state, chunk):
        xs, ys, masks, presents = chunk
        return self.setup.train_many(state, xs, ys, masks, presents)

    def defer_extras(self, chunk, fetch_s, k):
        extras = {"t_fetch": round(fetch_s / k, 6)}
        presents = chunk[3]
        if presents is not None:
            extras["present"] = presents.sum(axis=1)
        return extras

    def should_log(self, step):
        return step % self.tr.cfg.log_every == 0 or step == 1

    def beat_extras(self):
        return self.tr._prefetch_depth()

    def boundary(self, end, state):
        from draco_tpu.utils import checkpoint as ckpt

        tr = self.tr
        tr.state = state
        tr.evaluate(end)
        if tr.cfg.train_dir:
            with tr.tracer.span("ckpt", at_step=end):
                ckpt.save(tr.cfg.train_dir, end, state,
                          compress=tr.cfg.compress_ckpt,
                          keep=tr.cfg.keep_checkpoints)

    def stop_requested(self, end):
        return self.tr._check_stop(end)

    def snap_stop(self, end, state, already_saved):
        self.tr.state = state
        self.tr._snap_stop(end, already_saved=already_saved)

    def cleanup(self):
        pass  # prefetchers close with the Trainer (close())

    # ---- autopilot actuation (control/autopilot.py) ----------------------
    def build_setup(self, cfg):
        """Build a regime's TrainSetup — the warm-swap cache's
        construction hook (called once per NEW regime)."""
        from draco_tpu.training.step import build_train_setup

        return build_train_setup(cfg, self.tr.mesh,
                                 dataset_name=self.tr.ds.name)

    def switch_regime(self, setup, label):
        self.setup = setup
        self.label = label

    def quarantine(self, worker, from_step):
        """Present-mask exclusion: the worker's rows stop arriving from
        ``from_step`` on — an erasure at a known position, decoded around
        exactly like a scheduled straggler."""
        sched = self.tr._straggle_schedule
        self._pre_quarantine[worker] = sched[:, worker].copy()
        sched[from_step:, worker] = True

    def readmit(self, worker, from_step):
        """Restore the worker's pre-quarantine schedule column from
        ``from_step`` on (seeded drops it would have had anyway stay)."""
        saved = self._pre_quarantine.pop(worker, None)
        sched = self.tr._straggle_schedule
        if saved is None:
            sched[from_step:, worker] = False
        else:
            sched[from_step:, worker] = saved[from_step:len(sched)]


class TokenChunkClient:
    """Client for the LM token routes (parallel/token_loop.py): a chunk
    payload is (tokens | (K,) step vector, masks, presents). Family swaps
    rebuild the route setup via ``rebuild`` when the route provided one
    (sp does); without it the autopilot still quarantines/readmits."""

    BASE_LABEL = "train_token_many"

    def __init__(self, setup, cfg, adv, straggle, prefetch, obs,
                 boundary_eval_ckpt, rebuild=None):
        self.setup = setup
        self.cfg = cfg
        self.adv = adv
        self.straggle = straggle
        self.prefetch = prefetch
        self.obs = obs
        self._boundary = boundary_eval_ckpt
        self._rebuild = rebuild
        self.label = self.BASE_LABEL
        # current regime's wire segmentation (ISSUE 16) — see
        # TrainerChunkClient.wire_segments
        self.wire_segments = int(getattr(cfg, "wire_segments", 1) or 1)
        self._device_gen = cfg.token_gen == "device"
        self._pre_quarantine = {}  # worker -> schedule column before it

    @property
    def metric_names(self):
        return self.setup.metric_names

    def assemble(self, i, ranges):
        s0, k = ranges[i]
        with self.obs.tracer.span("gather", chunk_start=s0, k=k):
            if self._device_gen:
                # the program regenerates the batches in-graph: upload K
                # scalars
                toks = np.arange(s0, s0 + k, dtype=np.int32)
            else:
                toks = self.prefetch.get(
                    ranges[i],
                    ranges[i + 1] if i + 1 < len(ranges) else None)
            # numpy (uncommitted) so jit treats the schedules as replicated
            masks = np.asarray(self.adv[s0 : s0 + k])
            presents = (
                np.asarray(~self.straggle[s0 : s0 + k])
                if self.straggle is not None
                else None
            )
        return toks, masks, presents

    def dispatch(self, state, chunk):
        toks, masks, presents = chunk
        return self.setup.train_token_many(state, toks, masks, presents)

    def defer_extras(self, chunk, fetch_s, k):
        return None

    def should_log(self, step):
        return step % self.cfg.log_every == 0

    def beat_extras(self):
        # prefetch extras only when a prefetcher EXISTS: the device
        # token-gen mode has no host prefetch path, and reporting a
        # constant depth 0 there would read as starvation to the incident
        # engine (ISSUE 13); stats() is the supervision restart counter
        pf_extra = {}
        if self.prefetch is not None:
            pf_extra["prefetch_depth"] = self.prefetch.depth
            if hasattr(self.prefetch, "stats"):
                pf_extra.update(self.prefetch.stats())
        return pf_extra

    def boundary(self, end, state):
        self._boundary(end, state)

    def stop_requested(self, end):
        from draco_tpu.parallel.token_loop import _stop_requested

        return _stop_requested(self.obs, end)

    def snap_stop(self, end, state, already_saved):
        from draco_tpu.parallel.token_loop import _snap_stop

        _snap_stop(self.cfg, state, end, self.obs,
                   already_saved=already_saved)

    def cleanup(self):
        if self.prefetch is not None:
            self.prefetch.close()

    # ---- autopilot actuation (control/autopilot.py) ----------------------
    @property
    def can_swap(self):
        return self._rebuild is not None

    def build_setup(self, cfg):
        if self._rebuild is None:
            raise RuntimeError(
                "token route launched without a setup rebuild hook — "
                "autopilot family swaps unavailable on this route")
        return self._rebuild(cfg)

    def switch_regime(self, setup, label):
        self.setup = setup
        self.label = label

    def quarantine(self, worker, from_step):
        self._pre_quarantine[worker] = self.straggle[:, worker].copy()
        self.straggle[from_step:, worker] = True

    def readmit(self, worker, from_step):
        saved = self._pre_quarantine.pop(worker, None)
        if saved is None:
            self.straggle[from_step:, worker] = False
        else:
            self.straggle[from_step:, worker] = \
                saved[from_step:len(self.straggle)]
