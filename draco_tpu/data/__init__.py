from draco_tpu.data.datasets import Dataset, load_dataset  # noqa: F401
from draco_tpu.data.batching import (  # noqa: F401
    get_batch,
    worker_batches_baseline,
    worker_batches_grouped,
    cyclic_global_batch,
)
