"""Deterministic batch construction for the three training approaches.

The reference's load-bearing batching invariants (SURVEY.md §2.1 rows 9, 10, 19):

  * baseline: each worker draws an independent shuffle.
  * maj_vote: all members of a repetition group share a shuffle seed, so the
    group computes *identical* batches every step (rep_worker.py:89) — the
    soundness condition of the bitwise majority vote.
  * cyclic: every worker addresses one deterministic *global* batch of
    n·B consecutive post-shuffle samples per step (get_batch with an explicit
    index range, cyclic_worker.py:91-96, datasets/utils.py:7-29) and computes
    the ŝ=2s+1 sub-batches its row of the support mask selects.

All return numpy arrays ready to be device_put with a leading worker axis.
"""

from __future__ import annotations

import numpy as np

from draco_tpu import rng as drng
from draco_tpu.data.datasets import Dataset


def get_batch(ds: Dataset, indices: np.ndarray):
    """Fetch an explicit index set as one batch (reference: datasets/utils.py:7-29)."""
    return ds.train_x[indices], ds.train_y[indices]


def _epoch_and_offset(step: int, batches_per_epoch: int):
    return step // batches_per_epoch, step % batches_per_epoch


def _perm_slice(perm: np.ndarray, off: int, batch_size: int, n_samples: int):
    idx = perm[(off * batch_size) % n_samples :][:batch_size]
    if len(idx) < batch_size:  # wrap
        idx = np.concatenate([idx, perm[: batch_size - len(idx)]])
    return idx


def indices_baseline(n_samples: int, step: int, num_workers: int, batch_size: int,
                     seed: int) -> np.ndarray:
    """(n·B,) flat sample indices — each worker has its own shuffle stream."""
    bpe = max(n_samples // batch_size, 1)
    epoch, off = _epoch_and_offset(step, bpe)
    return np.concatenate([
        _perm_slice(drng.epoch_permutation(seed + 31 * (w + 1), epoch, n_samples),
                    off, batch_size, n_samples)
        for w in range(num_workers)
    ])


def indices_grouped(n_samples: int, step: int, num_workers: int, group_size: int,
                    batch_size: int, seeds: np.ndarray) -> np.ndarray:
    """(n·B,) flat indices where group members share the shuffle (identical
    batches within a group). ``seeds`` from rng.group_seeds."""
    bpe = max(n_samples // batch_size, 1)
    epoch, off = _epoch_and_offset(step, bpe)
    return np.concatenate([
        _perm_slice(drng.epoch_permutation(int(seeds[w // group_size]), epoch, n_samples),
                    off, batch_size, n_samples)
        for w in range(num_workers)
    ])


def indices_cyclic(n_samples: int, step: int, num_workers: int, batch_size: int,
                   seed: int) -> np.ndarray:
    """(n·B,) flat indices of the step's deterministic global batch.

    Mirrors the reference's batch_bias walk over an epoch-shuffled dataset
    (cyclic_worker.py:88-96) with the shared seed folded per epoch.
    """
    global_bs = num_workers * batch_size
    bpe = max(n_samples // global_bs, 1)
    epoch, off = _epoch_and_offset(step, bpe)
    perm = drng.epoch_permutation(seed, epoch, n_samples)
    start = off * global_bs
    idx = perm[start : start + global_bs]
    if len(idx) < global_bs:
        idx = np.concatenate([idx, perm[: global_bs - len(idx)]])
    return idx


# ---- vectorized step ranges (the scan-chunked trainer's index path) -------
#
# The chunked loop (training/trainer.py, cfg.steps_per_call > 1) feeds K
# steps per device program, so it wants all K steps' indices at once. Each
# *_range function returns a (k, n·B) block whose row i is bitwise identical
# to the per-step function at step0 + i — the equivalence the chunked-vs-
# eager trainer tests pin. One permutation fetch per (stream, epoch) instead
# of per step; the slice-with-wrap is one fancy-index gather.


def _perm_rows(perm_for_epoch, epochs: np.ndarray, cols: np.ndarray) -> np.ndarray:
    """Gather ``perm_for_epoch(e)[cols[i]]`` for each step row i (epochs[i]=e),
    fetching each epoch's permutation once."""
    out = np.empty(cols.shape, dtype=np.int64)
    for e in np.unique(epochs):
        rows = epochs == e
        out[rows] = perm_for_epoch(int(e))[cols[rows]]
    return out


def _range_cols(offs: np.ndarray, width: int, n_samples: int) -> np.ndarray:
    """(k, width) positions of each step's slice, wrap folded in: identical to
    ``_perm_slice``'s take-then-wrap for every width <= n_samples."""
    starts = (offs * width) % n_samples
    return (starts[:, None] + np.arange(width)[None, :]) % n_samples


def indices_baseline_range(n_samples: int, step0: int, k: int, num_workers: int,
                           batch_size: int, seed: int) -> np.ndarray:
    """(k, n·B) stacked flat indices; row i == indices_baseline(step0 + i)."""
    bpe = max(n_samples // batch_size, 1)
    steps = np.arange(step0, step0 + k)
    epochs, offs = steps // bpe, steps % bpe
    cols = _range_cols(offs, batch_size, n_samples)
    out = np.empty((k, num_workers * batch_size), dtype=np.int64)
    for w in range(num_workers):
        out[:, w * batch_size : (w + 1) * batch_size] = _perm_rows(
            lambda e, w=w: drng.epoch_permutation(seed + 31 * (w + 1), e, n_samples),
            epochs, cols,
        )
    return out


def indices_grouped_range(n_samples: int, step0: int, k: int, num_workers: int,
                          group_size: int, batch_size: int,
                          seeds: np.ndarray) -> np.ndarray:
    """(k, n·B) stacked flat indices; row i == indices_grouped(step0 + i)."""
    bpe = max(n_samples // batch_size, 1)
    steps = np.arange(step0, step0 + k)
    epochs, offs = steps // bpe, steps % bpe
    cols = _range_cols(offs, batch_size, n_samples)
    out = np.empty((k, num_workers * batch_size), dtype=np.int64)
    for w in range(num_workers):
        out[:, w * batch_size : (w + 1) * batch_size] = _perm_rows(
            lambda e, w=w: drng.epoch_permutation(
                int(seeds[w // group_size]), e, n_samples),
            epochs, cols,
        )
    return out


def indices_cyclic_range(n_samples: int, step0: int, k: int, num_workers: int,
                         batch_size: int, seed: int) -> np.ndarray:
    """(k, n·B) stacked flat indices; row i == indices_cyclic(step0 + i)."""
    global_bs = num_workers * batch_size
    bpe = max(n_samples // global_bs, 1)
    steps = np.arange(step0, step0 + k)
    epochs, offs = steps // bpe, steps % bpe
    cols = _range_cols(offs, global_bs, n_samples)
    return _perm_rows(
        lambda e: drng.epoch_permutation(seed, e, n_samples), epochs, cols
    )


def gather(ds: Dataset, idx: np.ndarray, num_workers: int, batch_size: int):
    """Indices -> (n, B, ...) batches + (n, B) labels."""
    x, y = get_batch(ds, idx)
    return (
        x.reshape((num_workers, batch_size) + x.shape[1:]),
        y.reshape(num_workers, batch_size),
    )


def worker_batches_baseline(ds: Dataset, step: int, num_workers: int, batch_size: int,
                            seed: int):
    """(n, B, ...) batches — each worker has its own shuffle stream."""
    idx = indices_baseline(len(ds), step, num_workers, batch_size, seed)
    return gather(ds, idx, num_workers, batch_size)


def worker_batches_grouped(ds: Dataset, step: int, num_workers: int, group_size: int,
                           batch_size: int, seeds: np.ndarray):
    """(n, B, ...) batches with per-group shared shuffles (rep_worker.py:89)."""
    idx = indices_grouped(len(ds), step, num_workers, group_size, batch_size, seeds)
    return gather(ds, idx, num_workers, batch_size)


def cyclic_global_batch(ds: Dataset, step: int, num_workers: int, batch_size: int,
                        seed: int):
    """(n, B, ...) — the global batch's n coded sub-batches; row k is
    sub-batch k, to be gathered per worker via code.batch_ids."""
    idx = indices_cyclic(len(ds), step, num_workers, batch_size, seed)
    return gather(ds, idx, num_workers, batch_size)


def chunk_ranges(start: int, last: int, steps_per_call: int,
                 eval_freq: int) -> list:
    """[(start, k), ...] covering 1-based steps [start, last]: chunks of up
    to ``steps_per_call`` steps, snapped so every ``eval_freq`` multiple (and
    the final step) ends a chunk — the explicit remainder chunks that keep
    eval/checkpoint cadence exact when the step count doesn't divide by K.

    The ONE chunk-boundary rule for every scan-fused loop (the CNN
    ``Trainer._run_chunked`` and the LM ``run_token_loop`` chunked regime) —
    a snapping fix here can't diverge between them.
    """
    K = max(steps_per_call, 1)
    out = []
    s = start
    while s <= last:
        e = min(s + K - 1, last)
        if eval_freq:
            e = min(e, ((s - 1) // eval_freq + 1) * eval_freq)
        out.append((s, e - s + 1))
        s = e + 1
    return out
