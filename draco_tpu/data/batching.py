"""Deterministic batch construction for the three training approaches.

The reference's load-bearing batching invariants (SURVEY.md §2.1 rows 9, 10, 19):

  * baseline: each worker draws an independent shuffle.
  * maj_vote: all members of a repetition group share a shuffle seed, so the
    group computes *identical* batches every step (rep_worker.py:89) — the
    soundness condition of the bitwise majority vote.
  * cyclic: every worker addresses one deterministic *global* batch of
    n·B consecutive post-shuffle samples per step (get_batch with an explicit
    index range, cyclic_worker.py:91-96, datasets/utils.py:7-29) and computes
    the ŝ=2s+1 sub-batches its row of the support mask selects.

All return numpy arrays ready to be device_put with a leading worker axis.
"""

from __future__ import annotations

import numpy as np

from draco_tpu import rng as drng
from draco_tpu.data.datasets import Dataset


def get_batch(ds: Dataset, indices: np.ndarray):
    """Fetch an explicit index set as one batch (reference: datasets/utils.py:7-29)."""
    return ds.train_x[indices], ds.train_y[indices]


def _epoch_and_offset(step: int, batches_per_epoch: int):
    return step // batches_per_epoch, step % batches_per_epoch


def worker_batches_baseline(ds: Dataset, step: int, num_workers: int, batch_size: int,
                            seed: int):
    """(n, B, ...) batches — each worker has its own shuffle stream."""
    n_samples = len(ds)
    bpe = max(n_samples // batch_size, 1)
    epoch, off = _epoch_and_offset(step, bpe)
    xs, ys = [], []
    for w in range(num_workers):
        perm = drng.epoch_permutation(seed + 31 * (w + 1), epoch, n_samples)
        idx = perm[(off * batch_size) % n_samples :][:batch_size]
        if len(idx) < batch_size:  # wrap
            idx = np.concatenate([idx, perm[: batch_size - len(idx)]])
        x, y = get_batch(ds, idx)
        xs.append(x)
        ys.append(y)
    return np.stack(xs), np.stack(ys)


def worker_batches_grouped(ds: Dataset, step: int, num_workers: int, group_size: int,
                           batch_size: int, seeds: np.ndarray):
    """(n, B, ...) batches where group members share the shuffle (identical
    batches within a group). ``seeds`` from rng.group_seeds."""
    n_samples = len(ds)
    bpe = max(n_samples // batch_size, 1)
    epoch, off = _epoch_and_offset(step, bpe)
    xs, ys = [], []
    for w in range(num_workers):
        g = w // group_size
        perm = drng.epoch_permutation(int(seeds[g]), epoch, n_samples)
        idx = perm[(off * batch_size) % n_samples :][:batch_size]
        if len(idx) < batch_size:
            idx = np.concatenate([idx, perm[: batch_size - len(idx)]])
        x, y = get_batch(ds, idx)
        xs.append(x)
        ys.append(y)
    return np.stack(xs), np.stack(ys)


def cyclic_global_batch(ds: Dataset, step: int, num_workers: int, batch_size: int,
                        seed: int):
    """(n, B, ...) — the step's global batch of n·B samples split into the n
    coded sub-batches, all addressed deterministically.

    Mirrors the reference's batch_bias walk over an epoch-shuffled dataset
    (cyclic_worker.py:88-96) with the shared seed folded per epoch; row k is
    sub-batch k, to be gathered per worker via code.batch_ids.
    """
    n_samples = len(ds)
    global_bs = num_workers * batch_size
    bpe = max(n_samples // global_bs, 1)
    epoch, off = _epoch_and_offset(step, bpe)
    perm = drng.epoch_permutation(seed, epoch, n_samples)
    start = off * global_bs
    idx = perm[start : start + global_bs]
    if len(idx) < global_bs:
        idx = np.concatenate([idx, perm[: global_bs - len(idx)]])
    x, y = get_batch(ds, idx)
    shape = (num_workers, batch_size) + x.shape[1:]
    return x.reshape(shape), y.reshape(num_workers, batch_size)
