"""Async host-side batch prefetch over the native gather engine.

The reference hid host data-prep latency behind separate DataLoader worker
processes (reference: src/data_loader_ops/my_data_loader.py:137-319). Here
the equivalent overlap comes from the native thread-pool gather
(native/loader.cpp): while the device executes step k, C++ threads assemble
step k+1's (n, B, ...) batch outside the GIL. Index computation (the epoch
permutations) stays in Python — it is microseconds; the row gather is the
bytes-heavy part.

Falls back to synchronous numpy gathering when the native library is absent,
so callers never branch.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

from draco_tpu import native
from draco_tpu.data.datasets import Dataset
from draco_tpu.obs.tracer import NULL_TRACER


class PrefetchStallError(RuntimeError):
    """A prefetcher queue wait exceeded its bound — the worker thread is
    dead or hung. Named (instead of blocking the main loop forever) so the
    supervisor can restart the prefetcher (resilience/supervisor.py) and
    operators can tell a stalled data path from a wedged device. Carries
    the stalled request, the timeout, and the tracer's last recorded span
    (the best available 'what was the worker doing' breadcrumb)."""

    def __init__(self, request, timeout_s: float, last_span=None):
        super().__init__(
            f"prefetch wait for request {request!r} exceeded "
            f"{timeout_s:g}s (worker thread dead or hung; last tracer "
            f"span: {last_span!r})"
        )
        self.request = request
        self.timeout_s = timeout_s
        self.last_span = last_span


class _PipelinedGather:
    """Submit/wait scaffolding shared by both prefetchers, keyed on an
    opaque hashable request (a step int, or a (start, k) chunk range).

    Subclasses provide ``_request_indices(key) -> sample indices`` (any
    shape; flattened for the gather) and ``_reshape(x, idx, key)``. ``_get``
    returns ``key``'s batch and immediately submits ``next_key``'s gather to
    the native pool (the pipeline overlap); synchronous numpy fallback when
    the native library is absent.
    """

    def __init__(self, ds: Dataset, num_workers: int, batch_size: int,
                 num_threads: int = 4, tracer=NULL_TRACER):
        self.ds = ds
        self.num_workers = num_workers
        self.batch_size = batch_size
        self._src = np.ascontiguousarray(ds.train_x)  # loader gathers raw rows
        self._loader: Optional[native.BatchLoader] = None
        self._tracer = tracer
        if native.AVAILABLE:
            self._loader = native.BatchLoader(num_threads)
        # (key, ticket, idx) of the request being assembled in the background
        self._inflight: Optional[tuple[Any, int, np.ndarray]] = None

    @property
    def depth(self) -> int:
        """In-flight background requests (0 or 1 — the pipeline is two-deep),
        the heartbeat's prefetch-queue-depth signal."""
        return int(self._inflight is not None)

    def _request_indices(self, key) -> np.ndarray:
        raise NotImplementedError

    def _reshape(self, x: np.ndarray, idx: np.ndarray, key):
        raise NotImplementedError

    def _get(self, key, next_key):
        tracer = self._tracer
        if self._loader is None:
            with tracer.span("prefetch.gather"):
                idx = self._request_indices(key)
                return self._reshape(self._src[idx.reshape(-1)], idx, key)
        if self._inflight is not None and self._inflight[0] == key:
            _, ticket, idx = self._inflight
            self._inflight = None
            # wait-time on the native pool: ~0 when the gather kept ahead of
            # the device, the host-side stall when it did not
            with tracer.span("prefetch.wait"):
                x = self._loader.wait(ticket)
        else:  # cold start / non-sequential access (e.g. resume)
            if self._inflight is not None:
                self._loader.wait(self._inflight[1])
                self._inflight = None
            with tracer.span("prefetch.gather"):
                idx = self._request_indices(key)
                x = self._loader.wait(
                    self._loader.submit(self._src, idx.reshape(-1)))
        batch = self._reshape(x, idx, key)
        if next_key is not None:
            nidx = self._request_indices(next_key)
            self._inflight = (
                next_key,
                self._loader.submit(self._src, nidx.reshape(-1)),
                nidx,
            )
        tracer.counter("prefetch_depth", self.depth)
        return batch

    def abandon(self):
        """Supervisor restart path: drop any in-flight request and release
        the loader best-effort (never raising — the instance is being
        replaced, not drained)."""
        self._inflight = None
        loader, self._loader = self._loader, None
        if loader is not None:
            try:
                loader.close()
            except Exception:
                pass

    def close(self):
        if self._loader is not None:
            if self._inflight is not None:
                self._loader.wait(self._inflight[1])
                self._inflight = None
            self._loader.close()
            self._loader = None


class BatchPrefetcher(_PipelinedGather):
    """Pipelined gather: ``get(step)`` returns step's batch, then immediately
    begins assembling ``step+1``'s in the background.

    indices_fn: step -> flat (n·B,) sample indices (deterministic, cheap).
    """

    def __init__(self, ds: Dataset, indices_fn: Callable[[int], np.ndarray],
                 num_workers: int, batch_size: int, num_threads: int = 4,
                 tracer=NULL_TRACER):
        super().__init__(ds, num_workers, batch_size, num_threads, tracer)
        self.indices_fn = indices_fn

    def _request_indices(self, step: int) -> np.ndarray:
        return self.indices_fn(step)

    def _reshape(self, x: np.ndarray, idx: np.ndarray, step):
        y = self.ds.train_y[idx].reshape(self.num_workers, self.batch_size)
        return x.reshape((self.num_workers, self.batch_size) + x.shape[1:]), y

    def get(self, step: int):
        return self._get(step, step + 1)


class ChunkPrefetcher(_PipelinedGather):
    """Stacked-chunk gather for the scan-fused trainer (cfg.steps_per_call>1).

    ``get((start, k), next_range)`` returns the (k, n, B, ...) batch block for
    steps [start, start+k) and immediately submits ``next_range``'s gather to
    the native thread pool, so the host assembles chunk i+1 while the device
    executes chunk i's fused program. One flat (k·n·B,) gather per chunk —
    the per-row cost is identical to the per-step path, the submit/wait
    round-trips are k× rarer.

    range_indices_fn: (start, k) -> (k, n·B) sample indices (the vectorized
    batching.indices_*_range family).
    """

    def __init__(self, ds: Dataset, range_indices_fn,
                 num_workers: int, batch_size: int, num_threads: int = 4,
                 tracer=NULL_TRACER):
        super().__init__(ds, num_workers, batch_size, num_threads, tracer)
        self.range_indices_fn = range_indices_fn

    def _request_indices(self, rng: tuple) -> np.ndarray:
        return self.range_indices_fn(*rng)

    def _reshape(self, x: np.ndarray, idx: np.ndarray, rng: tuple):
        k = rng[1]
        n, b = self.num_workers, self.batch_size
        y = self.ds.train_y[idx.reshape(-1)].reshape(k, n, b)
        return x.reshape((k, n, b) + x.shape[1:]), y

    def get(self, rng: tuple, next_range: Optional[tuple] = None):
        return self._get(tuple(rng),
                         tuple(next_range) if next_range is not None else None)


class TokenChunkPrefetcher:
    """Stacked-chunk assembly for the chunked LM token loop
    (parallel/token_loop.py, cfg.steps_per_call > 1).

    Same double-buffer contract as :class:`ChunkPrefetcher`, but the
    per-step "gather" is synthetic token *generation* (sp_step.synthetic_text
    — numpy, no dataset rows), so the background engine is a single worker
    thread instead of the native row-gather pool: ``get((start, k),
    next_range)`` returns the (k, n, B, T) int32 block for steps
    [start, start + k) and immediately submits ``next_range``'s generation,
    so the host builds chunk i+1 while the device executes chunk i.

    gen_fn: step -> (n, B, T) tokens (deterministic, per-step).

    ``tracer``: optional SpanTracer — the worker thread labels its own
    trace lane and emits one ``prefetch.assemble`` span per chunk, so the
    trace shows the assembly racing the device's chunk execution;
    ``prefetch_depth`` counter events track the in-flight request (one
    counter name for this signal everywhere: both prefetcher families and
    the status.json heartbeat key).
    """

    def __init__(self, gen_fn: Callable[[int], np.ndarray],
                 tracer=NULL_TRACER, timeout_s: float = 0.0):
        import concurrent.futures

        self._gen = gen_fn
        self._tracer = tracer
        # bound on any single queue wait (0 = wait forever, the historical
        # behavior): a dead/hung worker raises the named PrefetchStallError
        # instead of wedging the main loop (ISSUE 6 satellite)
        self._timeout_s = float(timeout_s)
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="token-chunk-prefetch",
            # labels the worker's trace lane (runs once, on the worker
            # thread itself, when it spins up; no-op on the null tracer)
            initializer=lambda: tracer.name_thread("token-chunk-prefetch"),
        )
        self._inflight: Optional[tuple] = None  # (range, future)
        self._stalled = False  # a stall was observed: never join this pool

    def _wait(self, rng: tuple, future):
        """Bounded wait on a worker future; a worker exception propagates
        as itself (concurrent.futures re-raises it here, on the main
        thread), a timeout becomes the named stall error."""
        import concurrent.futures

        try:
            return future.result(self._timeout_s or None)
        except concurrent.futures.TimeoutError:
            # the worker is hung; remember it so close() abandons instead
            # of re-wedging the loop on shutdown(wait=True)
            self._stalled = True
            raise PrefetchStallError(rng, self._timeout_s,
                                     self._tracer.last_span) from None

    @property
    def depth(self) -> int:
        """In-flight background assemblies (0 or 1), the heartbeat's
        prefetch-queue-depth signal."""
        return int(self._inflight is not None)

    def _assemble(self, rng: tuple) -> np.ndarray:
        start, k = rng
        with self._tracer.span("prefetch.assemble", chunk_start=start, k=k):
            return np.stack([self._gen(step)
                             for step in range(start, start + k)])

    def get(self, rng: tuple, next_range: Optional[tuple] = None) -> np.ndarray:
        rng = tuple(rng)
        if self._inflight is not None and self._inflight[0] == rng:
            with self._tracer.span("prefetch.wait"):
                inflight, self._inflight = self._inflight, None
                block = self._wait(rng, inflight[1])
        else:  # cold start / non-sequential access (e.g. resume)
            if self._inflight is not None:
                inflight, self._inflight = self._inflight, None
                self._wait(inflight[0], inflight[1])
            # cold-start assembly ALSO runs on the worker under the bounded
            # wait: assembling inline on the main thread would turn a
            # persistently hung source into an untimeboxable main-thread
            # hang on the supervisor's very first retry
            block = self._wait(rng, self._pool.submit(self._assemble, rng))
        if next_range is not None:
            nxt = tuple(next_range)
            self._inflight = (nxt, self._pool.submit(self._assemble, nxt))
        self._tracer.counter("prefetch_depth", self.depth)
        return block

    def abandon(self):
        """Drop everything without waiting — for the supervisor's restart
        path, where the worker may be hung and close()'s drain would wedge
        the supervisor too. The abandoned worker runs on in the background
        and NOTHING IN-PROCESS joins it; the one residual is Python's own
        interpreter-shutdown join of executor threads
        (concurrent.futures' atexit hook), so a worker hung FOREVER (not
        just slow) still stalls process exit — a bounded main loop cannot
        fully absolve an unbounded thread."""
        self._inflight = None
        self._pool.shutdown(wait=False, cancel_futures=True)

    def close(self):
        if self._stalled:
            # a hung worker was already detected: joining it would re-wedge
            # the loop the queue-wait bound exists to protect
            self.abandon()
            return
        if self._inflight is not None:
            inflight, self._inflight = self._inflight, None
            try:
                self._wait(inflight[0], inflight[1])
            except Exception:
                pass  # closing: a failed/stalled tail fetch is discarded
        if self._stalled:  # ...including one that stalled just now
            self.abandon()
            return
        self._pool.shutdown(wait=True)
