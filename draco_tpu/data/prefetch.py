"""Async host-side batch prefetch over the native gather engine.

The reference hid host data-prep latency behind separate DataLoader worker
processes (reference: src/data_loader_ops/my_data_loader.py:137-319). Here
the equivalent overlap comes from the native thread-pool gather
(native/loader.cpp): while the device executes step k, C++ threads assemble
step k+1's (n, B, ...) batch outside the GIL. Index computation (the epoch
permutations) stays in Python — it is microseconds; the row gather is the
bytes-heavy part.

Falls back to synchronous numpy gathering when the native library is absent,
so callers never branch.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from draco_tpu import native
from draco_tpu.data.datasets import Dataset


class BatchPrefetcher:
    """Pipelined gather: ``get(step)`` returns step's batch, then immediately
    begins assembling ``step+1``'s in the background.

    indices_fn: step -> flat (n·B,) sample indices (deterministic, cheap).
    """

    def __init__(self, ds: Dataset, indices_fn: Callable[[int], np.ndarray],
                 num_workers: int, batch_size: int, num_threads: int = 4):
        self.ds = ds
        self.indices_fn = indices_fn
        self.num_workers = num_workers
        self.batch_size = batch_size
        self._src = np.ascontiguousarray(ds.train_x)  # loader gathers raw rows
        self._loader: Optional[native.BatchLoader] = None
        if native.AVAILABLE:
            self._loader = native.BatchLoader(num_threads)
        self._inflight: Optional[tuple[int, int, np.ndarray]] = None  # (step, ticket, idx)

    def _reshape(self, x: np.ndarray, idx: np.ndarray):
        y = self.ds.train_y[idx].reshape(self.num_workers, self.batch_size)
        return x.reshape((self.num_workers, self.batch_size) + x.shape[1:]), y

    def get(self, step: int):
        if self._loader is None:
            idx = self.indices_fn(step)
            return self._reshape(self._src[idx], idx)
        if self._inflight is not None and self._inflight[0] == step:
            _, ticket, idx = self._inflight
            self._inflight = None
            x = self._loader.wait(ticket)
        else:  # cold start / non-sequential access (e.g. resume)
            if self._inflight is not None:
                self._loader.wait(self._inflight[1])
                self._inflight = None
            idx = self.indices_fn(step)
            ticket = self._loader.submit(self._src, idx)
            x = self._loader.wait(ticket)
        batch = self._reshape(x, idx)
        nxt = step + 1
        nidx = self.indices_fn(nxt)
        self._inflight = (nxt, self._loader.submit(self._src, nidx), nidx)
        return batch

    def close(self):
        if self._loader is not None:
            if self._inflight is not None:
                self._loader.wait(self._inflight[1])
                self._inflight = None
            self._loader.close()
            self._loader = None
