"""Dataset ingestion — MNIST / CIFAR-10 from raw files, synthetic fallback.

The reference pulls MNIST/CIFAR-10 through torchvision with download=True
(src/util.py:23-66). This image has no torchvision and no network egress, so:

  * ``MNIST`` / ``Cifar10`` load from raw files if present under data_dir
    (idx-ubyte files / cifar-10-batches-py pickles — the standard layouts),
  * otherwise a deterministic class-conditional synthetic set with identical
    shapes/normalisation is generated (clearly labelled in metadata), so
    every pipeline and benchmark runs end-to-end anywhere.
  * ``synthetic-mnist`` / ``synthetic-cifar10`` request the synthetic set
    explicitly.

Normalisation constants match the reference exactly: MNIST (0.1307, 0.3081)
(util.py:33), CIFAR-10 mean/std per channel in 0-255 units (util.py:37-38).
Arrays are NHWC float32, labels int32.
"""

from __future__ import annotations

import dataclasses
import gzip
import os
import pickle
from typing import Optional

import numpy as np

MNIST_MEAN, MNIST_STD = 0.1307, 0.3081
CIFAR_MEAN = np.array([125.3, 123.0, 113.9], dtype=np.float32) / 255.0
CIFAR_STD = np.array([63.0, 62.1, 66.7], dtype=np.float32) / 255.0


@dataclasses.dataclass
class Dataset:
    name: str
    train_x: np.ndarray  # (N, H, W, C) float32, normalised
    train_y: np.ndarray  # (N,) int32
    test_x: np.ndarray
    test_y: np.ndarray
    num_classes: int = 10
    synthetic: bool = False

    def __len__(self):
        return len(self.train_x)


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        data = f.read()
    magic = int.from_bytes(data[0:4], "big")
    ndim = magic & 0xFF
    dims = [int.from_bytes(data[4 + 4 * i : 8 + 4 * i], "big") for i in range(ndim)]
    return np.frombuffer(data, dtype=np.uint8, offset=4 + 4 * ndim).reshape(dims)


def _find(root: str, names) -> Optional[str]:
    for name in names:
        for cand in (os.path.join(root, name), os.path.join(root, name + ".gz")):
            if os.path.exists(cand):
                return cand
    return None


def _try_load_mnist(data_dir: str) -> Optional[Dataset]:
    roots = [data_dir, os.path.join(data_dir, "mnist"), os.path.join(data_dir, "MNIST", "raw")]
    for root in roots:
        tri = _find(root, ["train-images-idx3-ubyte", "train-images.idx3-ubyte"])
        trl = _find(root, ["train-labels-idx1-ubyte", "train-labels.idx1-ubyte"])
        tei = _find(root, ["t10k-images-idx3-ubyte", "t10k-images.idx3-ubyte"])
        tel = _find(root, ["t10k-labels-idx1-ubyte", "t10k-labels.idx1-ubyte"])
        if all([tri, trl, tei, tel]):
            norm = lambda x: ((x.astype(np.float32) / 255.0 - MNIST_MEAN) / MNIST_STD)[..., None]
            return Dataset(
                name="MNIST",
                train_x=norm(_read_idx(tri)),
                train_y=_read_idx(trl).astype(np.int32),
                test_x=norm(_read_idx(tei)),
                test_y=_read_idx(tel).astype(np.int32),
            )
    return None


def _try_load_cifar10(data_dir: str) -> Optional[Dataset]:
    for root in [data_dir, os.path.join(data_dir, "cifar10"), os.path.join(data_dir, "cifar10_data")]:
        batch_dir = os.path.join(root, "cifar-10-batches-py")
        if not os.path.isdir(batch_dir):
            continue
        xs, ys = [], []
        for i in range(1, 6):
            with open(os.path.join(batch_dir, f"data_batch_{i}"), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(d[b"data"])
            ys.append(d[b"labels"])
        with open(os.path.join(batch_dir, "test_batch"), "rb") as f:
            d = pickle.load(f, encoding="bytes")

        def norm(raw):
            x = raw.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1).astype(np.float32) / 255.0
            return (x - CIFAR_MEAN) / CIFAR_STD

        return Dataset(
            name="Cifar10",
            train_x=norm(np.concatenate(xs)),
            train_y=np.concatenate(ys).astype(np.int32),
            test_x=norm(d[b"data"]),
            test_y=np.asarray(d[b"labels"], dtype=np.int32),
        )
    return None


def _synthetic(name: str, shape, n_train: int, n_test: int, seed: int = 1234) -> Dataset:
    """Class-conditional Gaussian blobs: learnable (a linear probe reaches
    high accuracy), deterministic, correct shapes/dtypes."""
    rng = np.random.RandomState(seed)
    h, w, c = shape
    num_classes = 10
    protos = rng.randn(num_classes, h, w, c).astype(np.float32)

    def make(n, salt):
        r = np.random.RandomState(seed + salt)
        y = r.randint(0, num_classes, size=n).astype(np.int32)
        x = 0.6 * protos[y] + 0.8 * r.randn(n, h, w, c).astype(np.float32)
        return x.astype(np.float32), y

    tx, ty = make(n_train, 1)
    ex, ey = make(n_test, 2)
    return Dataset(name=name, train_x=tx, train_y=ty, test_x=ex, test_y=ey, synthetic=True)


def load_dataset(dataset: str, data_dir: str = "./data", synthetic_train: int = 8192,
                 synthetic_test: int = 2048) -> Dataset:
    key = dataset.lower()
    if key == "mnist":
        ds = _try_load_mnist(data_dir)
        if ds is not None:
            return ds
        return _synthetic("synthetic-mnist", (28, 28, 1), synthetic_train, synthetic_test)
    if key in ("cifar10", "cifar-10"):
        ds = _try_load_cifar10(data_dir)
        if ds is not None:
            return ds
        return _synthetic("synthetic-cifar10", (32, 32, 3), synthetic_train, synthetic_test)
    if key == "synthetic-mnist":
        return _synthetic("synthetic-mnist", (28, 28, 1), synthetic_train, synthetic_test)
    if key in ("synthetic-cifar10", "synthetic-cifar"):
        return _synthetic("synthetic-cifar10", (32, 32, 3), synthetic_train, synthetic_test)
    raise ValueError(f"unknown dataset: {dataset}")
