"""On-device data augmentation (CIFAR-10 policy of the reference:
reflect-pad 4, random 32×32 crop, random horizontal flip — util.py:42-52;
MNIST gets normalisation only).

Runs inside the jitted step on the worker-sharded batch, so augmentation
cost rides the accelerator and determinism is a property of the rng key:
the trainer folds the key per (step, group-or-row), which keeps repetition
group members' batches bitwise identical (vote soundness) and cyclic batch
rows worker-independent (decode exactness).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _augment_one(x: jnp.ndarray, key: jax.Array, pad: int = 4) -> jnp.ndarray:
    """x: (H, W, C) — reflect-pad, random crop back to (H, W), random flip."""
    h, w, _ = x.shape
    kh, kw, kf = jax.random.split(key, 3)
    xp = jnp.pad(x, ((pad, pad), (pad, pad), (0, 0)), mode="reflect")
    top = jax.random.randint(kh, (), 0, 2 * pad + 1)
    left = jax.random.randint(kw, (), 0, 2 * pad + 1)
    x = jax.lax.dynamic_slice(xp, (top, left, 0), (h, w, x.shape[2]))
    flip = jax.random.bernoulli(kf)
    return jnp.where(flip, x[:, ::-1, :], x)


def augment_batch(x: jnp.ndarray, key: jax.Array, pad: int = 4) -> jnp.ndarray:
    """x: (B, H, W, C); per-sample independent draws from ``key``."""
    keys = jax.random.split(key, x.shape[0])
    return jax.vmap(_augment_one, in_axes=(0, 0, None))(x, keys, pad)
